"""Command-line training entry — ``parallelism/main/ParallelWrapperMain.java``
parity (the reference ships a CLI that loads a serialized model and trains it
data-parallel with optional UI).

Usage:
    python -m deeplearning4j_tpu.cli train --model net.zip --csv data.csv \
        --label-index -1 --num-classes 3 --epochs 5 [--parallel shared_gradients]
        [--batch 32] [--ui-port 9001] [--save out.zip]
    python -m deeplearning4j_tpu.cli summary --model net.zip
"""

from __future__ import annotations

import argparse
import sys


def _load_model(path: str):
    from .train.serialization import load_model

    model, *_ = load_model(path)
    return model


def cmd_summary(args) -> int:
    model = _load_model(args.model)
    print(model.summary() if hasattr(model, "summary") else model.to_json())
    return 0


def cmd_train(args) -> int:
    if not args.regression and args.num_classes < 1:
        print("error: --num-classes is required for classification "
              "(or pass --regression)", file=sys.stderr)
        return 2
    import numpy as np

    from .data.records import (CSVRecordReader, RecordReaderDataSetIterator,
                               TransformProcess)
    from .train import Trainer
    from .train.listeners import ScoreIterationListener

    model = _load_model(args.model)
    it = RecordReaderDataSetIterator(
        CSVRecordReader(args.csv, skip_lines=args.skip_lines), args.batch,
        label_index=args.label_index, num_classes=args.num_classes,
        regression=args.regression)

    listeners = [ScoreIterationListener(args.print_every)]
    ui_server = None
    if args.ui_port:
        from .ui import InMemoryStatsStorage, StatsListener, UIServer

        storage = InMemoryStatsStorage()
        ui_server = UIServer(storage, port=args.ui_port).start()
        listeners.append(StatsListener(storage, session_id="cli"))
        print(f"training UI at http://127.0.0.1:{ui_server.port}/", file=sys.stderr)

    import os

    if os.environ.get("DL4J_TPU_MULTIHOST"):
        # pod-slice launch (utils/provision.py multihost_train_plan): every
        # host runs this same command; bootstrap the global mesh and give
        # this process its row-stripe of the CSV as its per-step shard
        if args.parallel:
            print("error: --parallel conflicts with DL4J_TPU_MULTIHOST "
                  "(the multi-host path owns the parallel topology)",
                  file=sys.stderr)
            return 2
        import jax

        from .parallel import (MultiHostTrainer, ProcessShardIterator,
                               initialize_multihost)

        initialize_multihost()  # auto-discovers the coordinator on TPU pods
        expected = int(os.environ.get("DL4J_TPU_NUM_HOSTS", "0"))
        if expected > 1 and jax.process_count() != expected:
            print(f"error: expected {expected} hosts "
                  f"(DL4J_TPU_NUM_HOSTS) but jax.process_count()="
                  f"{jax.process_count()} — distributed init did not form "
                  f"the full pod; refusing to train {expected} independent "
                  f"copies", file=sys.stderr)
            return 3
        feats, labels = [], []
        for ds in it:
            feats.append(np.asarray(ds.features))
            labels.append(np.asarray(ds.labels))
        trainer = MultiHostTrainer(model)
        it = ProcessShardIterator(np.concatenate(feats), np.concatenate(labels),
                                  global_batch_size=args.batch)
    elif args.parallel:
        from .parallel import ParallelWrapper

        trainer = ParallelWrapper(model, mode=args.parallel)
    else:
        trainer = Trainer(model)
    try:
        trainer.fit(it, epochs=args.epochs, listeners=listeners)
    finally:
        if ui_server is not None:
            ui_server.stop()
    if args.save:
        trainer.save(args.save)
        print(f"saved -> {args.save}", file=sys.stderr)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summary", help="print a serialized model's structure")
    s.add_argument("--model", required=True)
    s.set_defaults(fn=cmd_summary)

    t = sub.add_parser("train", help="train a serialized model on a CSV")
    t.add_argument("--model", required=True, help="model zip (serialization format)")
    t.add_argument("--csv", required=True)
    t.add_argument("--label-index", type=int, default=-1)
    t.add_argument("--num-classes", type=int, default=0)
    t.add_argument("--regression", action="store_true")
    t.add_argument("--skip-lines", type=int, default=0)
    t.add_argument("--batch", type=int, default=32)
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--parallel", choices=["shared_gradients", "zero_sharded",
                                          "averaging", "encoded_gradients"],
                   default=None)
    t.add_argument("--print-every", type=int, default=10)
    t.add_argument("--ui-port", type=int, default=0)
    t.add_argument("--save", default=None)
    t.set_defaults(fn=cmd_train)
    return p


def main(argv=None) -> int:
    import os

    if os.environ.get("JAX_PLATFORMS"):
        # mirror the env var into jax config: the hosting image's site hook
        # can override the env-var-only path (and a wedged accelerator
        # tunnel then hangs device init even for JAX_PLATFORMS=cpu runs)
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
