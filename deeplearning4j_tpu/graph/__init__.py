"""Graph embeddings — deeplearning4j-graph equivalent (SURVEY.md §2.9).

In-memory graph API, random-walk iterators, and DeepWalk built on the shared
SequenceVectors skip-gram machinery (hierarchical softmax over a degree-based
Huffman tree, GraphHuffman parity).
"""

from .graph import Edge, Graph, load_delimited_edges, load_weighted_edges
from .walks import RandomWalkIterator, WeightedRandomWalkIterator
from .deepwalk import DeepWalk
from .node2vec import Node2Vec

__all__ = ["Edge", "Graph", "DeepWalk", "Node2Vec", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "load_delimited_edges",
           "load_weighted_edges"]
