"""DeepWalk — ``graph/models/deepwalk/DeepWalk.java`` (255 LoC) equivalent.

The reference trains skip-gram with hierarchical softmax over a Huffman tree
built on vertex degrees (``GraphHuffman.java``, 8-connected binary tree coded
by degree as frequency). Here DeepWalk composes the shared pieces TPU-first:

- walks: vectorized ``RandomWalkIterator`` batches (host ETL)
- vocab: one VocabWord per vertex, count = degree → the existing Huffman
  builder (``nlp/vocab.py``) reproduces GraphHuffman's code assignment
- training: ``SequenceVectors`` with ``negative=0`` → the jitted batched
  hierarchical-softmax skip-gram step (one fused device step per batch,
  replacing the reference's per-pair scalar loop).

API parity: ``initialize``, ``fit(iterator)``, ``get_vertex_vector``,
``similarity``, ``verticesNearest`` (via SequenceVectors.nearest).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..nlp.sequencevectors import SequenceVectors, SkipGram
from ..nlp.vocab import VocabCache, VocabWord, build_huffman
from .graph import Graph
from .walks import RandomWalkIterator


class DeepWalk:
    """DeepWalk.Builder parity: vectorSize, windowSize, learningRate, seed;
    ``fit(graph, walk_length)`` runs walks + skip-gram-HS in one call."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.01, epochs: int = 1,
                 batch_size: int = 2048, seed: int = 12345):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.sv: Optional[SequenceVectors] = None

    # DeepWalk.java initialize(graph): build degree-frequency Huffman tree
    def initialize(self, graph: Graph) -> None:
        cache = VocabCache()
        degrees = graph.degrees()
        for v in range(graph.n):
            # degree 0 still gets a leaf (reference uses degree as frequency)
            cache.add(VocabWord(word=str(v), count=max(int(degrees[v]), 1)))
        cache.total_count = int(sum(max(int(d), 1) for d in degrees))
        build_huffman(cache)
        self.sv = SequenceVectors(cache, layer_size=self.vector_size,
                                  window=self.window_size, negative=0,
                                  learning_rate=self.learning_rate,
                                  min_learning_rate=self.learning_rate * 1e-2,
                                  epochs=self.epochs, batch_size=self.batch_size,
                                  seed=self.seed, algorithm=SkipGram())

    def fit(self, graph: Graph, walk_length: int = 40,
            walks: Optional[Iterable[np.ndarray]] = None) -> List[float]:
        """Run random walks and train; pass ``walks`` to use a custom iterator
        (weighted walks, precomputed corpora...)."""
        if self.sv is None:
            self.initialize(graph)
        if walks is None:
            walks = RandomWalkIterator(graph, walk_length, seed=self.seed)
        return self.sv.fit(list(walks))

    # --- GraphVectors surface (models/GraphVectors.java) ---
    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self.sv.vector(v)

    @property
    def vectors(self) -> np.ndarray:
        return self.sv.vectors

    def similarity(self, a: int, b: int) -> float:
        return self.sv.similarity(a, b)

    def vertices_nearest(self, v: int, top_n: int = 10) -> List[Tuple[int, float]]:
        return self.sv.nearest(v, top_n)
