"""In-memory graph — parity with ``graph/api/IGraph.java`` + ``graph/Graph.java``.

The reference stores vertices as objects with a value payload and adjacency
lists of Edge objects. Here the graph is CSR-style numpy adjacency (offsets +
targets + weights) built once from an edge list — the layout random-walk
generation wants (vectorized sampling over contiguous neighbor slices), and
the natural host-side feed for device-batched DeepWalk training.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


class NoEdgesException(Exception):
    """Walk hit a vertex with no outgoing edges under NoEdgeHandling.EXCEPTION
    (``graph/exception/NoEdgesException.java``)."""


@dataclass(frozen=True)
class Edge(object):
    """``graph/api/Edge.java`` — directed flag matches the reference."""

    src: int
    dst: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """``graph/Graph.java`` — vertices are 0..n-1; optional value payloads
    (VertexFactory equivalent is just the ``values`` list)."""

    def __init__(self, n_vertices: int, edges: Iterable[Edge] = (),
                 values: Optional[Sequence] = None):
        self.n = int(n_vertices)
        self.values = list(values) if values is not None else None
        adj: List[List[Tuple[int, float]]] = [[] for _ in range(self.n)]
        for e in edges:
            adj[e.src].append((e.dst, e.weight))
            if not e.directed:
                adj[e.dst].append((e.src, e.weight))
        counts = np.array([len(a) for a in adj], np.int64)
        self.offsets = np.zeros(self.n + 1, np.int64)
        np.cumsum(counts, out=self.offsets[1:])
        self.targets = np.zeros(int(self.offsets[-1]), np.int64)
        self.weights = np.zeros(int(self.offsets[-1]), np.float64)
        for v, nbrs in enumerate(adj):
            o = self.offsets[v]
            for k, (t, w) in enumerate(nbrs):
                self.targets[o + k] = t
                self.weights[o + k] = w

    # --- IGraph surface ---
    def num_vertices(self) -> int:
        return self.n

    def num_edges(self) -> int:
        return int(self.offsets[-1])

    def degree(self, v: int) -> int:
        return int(self.offsets[v + 1] - self.offsets[v])

    def degrees(self) -> np.ndarray:
        return np.diff(self.offsets)

    def neighbors(self, v: int) -> np.ndarray:
        return self.targets[self.offsets[v]: self.offsets[v + 1]]

    def neighbor_weights(self, v: int) -> np.ndarray:
        return self.weights[self.offsets[v]: self.offsets[v + 1]]

    def vertex_value(self, v: int):
        return self.values[v] if self.values is not None else v


def load_delimited_edges(path: str, n_vertices: int, delim: str = ",",
                         directed: bool = False) -> Graph:
    """``data/impl/DelimitedEdgeLineProcessor.java`` + ``GraphLoader`` — each
    line "src<delim>dst"; blank lines and ``//`` comments skipped."""
    edges = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("//"):
                continue
            a, b = line.split(delim)[:2]
            edges.append(Edge(int(a), int(b), directed=directed))
    return Graph(n_vertices, edges)


def load_weighted_edges(path: str, n_vertices: int, delim: str = ",",
                        directed: bool = False) -> Graph:
    """``data/impl/WeightedEdgeLineProcessor.java`` — "src<delim>dst<delim>w"."""
    edges = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("//"):
                continue
            parts = line.split(delim)
            edges.append(Edge(int(parts[0]), int(parts[1]), float(parts[2]),
                              directed=directed))
    return Graph(n_vertices, edges)
