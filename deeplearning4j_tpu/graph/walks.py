"""Random-walk iterators — ``graph/iterator/RandomWalkIterator.java`` and
``WeightedRandomWalkIterator.java``.

The reference walks one vertex at a time through object adjacency lists; here
walks are generated in vectorized batches over the CSR arrays (one
``np.random`` gather per step for the whole batch), which keeps the host-side
ETL fast enough to saturate the device-batched skip-gram step.

NoEdgeHandling parity: SELF_LOOP_ON_DISCONNECTED (default here, walk stays)
or EXCEPTION_ON_DISCONNECTED (raise NoEdgesException).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from .graph import Graph, NoEdgesException


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex (shuffled order),
    matching RandomWalkIterator semantics: each epoch yields one walk per
    starting vertex."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 12345,
                 no_edge_handling: str = "self_loop", batch: int = 512):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.batch = batch

    def _step(self, current: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        g = self.graph
        deg = g.offsets[current + 1] - g.offsets[current]
        if self.no_edge_handling == "exception" and np.any(deg == 0):
            raise NoEdgesException(
                f"Vertex {int(current[np.argmax(deg == 0)])} has no edges")
        # disconnected vertices self-loop; others pick a uniform neighbor
        pick = (rng.random(len(current)) * np.maximum(deg, 1)).astype(np.int64)
        nxt = g.targets[np.minimum(g.offsets[current] + pick,
                                   len(g.targets) - 1 if len(g.targets) else 0)] \
            if len(g.targets) else current
        return np.where(deg > 0, nxt, current)

    def __iter__(self) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        order = rng.permutation(self.graph.n)
        for s in range(0, len(order), self.batch):
            starts = order[s: s + self.batch]
            walk = np.empty((len(starts), self.walk_length + 1), np.int64)
            walk[:, 0] = starts
            cur = starts
            for t in range(self.walk_length):
                cur = self._step(cur, rng)
                walk[:, t + 1] = cur
            yield from walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """``WeightedRandomWalkIterator.java`` — transition probability
    proportional to edge weight.

    Vectorized like the uniform walker: one prefix-sum of all edge weights is
    built lazily, then each step is a single ``searchsorted`` over the whole
    batch (inverse-CDF sampling within each vertex's CSR slice)."""

    def _step(self, current: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        g = self.graph
        if not hasattr(self, "_prefix"):
            self._prefix = np.concatenate([[0.0], np.cumsum(g.weights)])
        deg = g.offsets[current + 1] - g.offsets[current]
        lo = self._prefix[g.offsets[current]] if len(g.targets) else np.zeros(0)
        hi = self._prefix[g.offsets[current + 1]] if len(g.targets) else lo
        # zero total weight is as stuck as zero degree: same handling
        stuck = (deg == 0) if len(g.targets) == 0 else (hi - lo <= 0)
        if self.no_edge_handling == "exception" and np.any(stuck):
            raise NoEdgesException(
                f"Vertex {int(current[np.argmax(stuck)])} has no traversable "
                f"edges (zero degree or zero total weight)")
        if len(g.targets) == 0:
            return current
        target = lo + rng.random(len(current)) * (hi - lo)
        pos = np.searchsorted(self._prefix, target, side="right") - 1
        pos = np.clip(pos, g.offsets[current],
                      np.maximum(g.offsets[current + 1] - 1, g.offsets[current]))
        return np.where(~stuck, g.targets[np.minimum(pos, len(g.targets) - 1)],
                        current)
