"""node2vec — second-order biased random walks + skip-gram.

The reference ships only a stub (``models/node2vec/``, SURVEY.md §2.5); this
is the full Grover & Leskovec 2016 algorithm: walk transition probability
reweighted by return parameter ``p`` and in-out parameter ``q`` relative to
the previous step, then the shared SequenceVectors skip-gram trainer
(negative sampling) on the walk corpus.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..nlp.sequencevectors import SequenceVectors, SkipGram
from ..nlp.vocab import VocabCache, VocabWord
from .graph import Graph


class Node2Vec:
    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 p: float = 1.0, q: float = 1.0, negative: int = 5,
                 learning_rate: float = 0.025, epochs: int = 1,
                 batch_size: int = 2048, seed: int = 12345):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.p = p
        self.q = q
        self.negative = negative
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.sv: Optional[SequenceVectors] = None

    def _biased_walks(self, g: Graph, rng: np.random.Generator) -> List[np.ndarray]:
        """Second-order walks: weight * (1/p if back, 1 if neighbor-of-prev,
        1/q otherwise). The per-step reweight is vectorized over the current
        vertex's whole neighbor slice (sorted-neighbor ``np.isin`` membership)
        instead of a per-edge Python loop."""
        sorted_nbrs = [np.sort(g.neighbors(v)) for v in range(g.n)]
        walks = []
        for _ in range(self.walks_per_vertex):
            for start in rng.permutation(g.n):
                walk = [int(start)]
                while len(walk) < self.walk_length + 1:
                    cur = walk[-1]
                    nbrs = g.neighbors(cur)
                    if len(nbrs) == 0:
                        break
                    w = g.neighbor_weights(cur).astype(np.float64).copy()
                    if len(walk) >= 2:
                        prev = walk[-2]
                        back = nbrs == prev
                        common = np.isin(nbrs, sorted_nbrs[prev],
                                         assume_unique=False)
                        w[back] /= self.p
                        w[~back & ~common] /= self.q
                    total = w.sum()
                    if total <= 0:
                        break
                    walk.append(int(nbrs[np.searchsorted(np.cumsum(w),
                                                         rng.random() * total)]))
                walks.append(np.asarray(walk, np.int64))
        return walks

    def fit(self, graph: Graph) -> List[float]:
        cache = VocabCache()
        degrees = graph.degrees()
        for v in range(graph.n):
            cache.add(VocabWord(word=str(v), count=max(int(degrees[v]), 1)))
        cache.total_count = int(sum(max(int(d), 1) for d in degrees))
        self.sv = SequenceVectors(cache, layer_size=self.vector_size,
                                  window=self.window_size, negative=self.negative,
                                  learning_rate=self.learning_rate,
                                  min_learning_rate=self.learning_rate * 1e-2,
                                  epochs=self.epochs, batch_size=self.batch_size,
                                  seed=self.seed, algorithm=SkipGram())
        rng = np.random.default_rng(self.seed)
        return self.sv.fit(self._biased_walks(graph, rng))

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self.sv.vector(v)

    @property
    def vectors(self) -> np.ndarray:
        return self.sv.vectors

    def similarity(self, a: int, b: int) -> float:
        return self.sv.similarity(a, b)

    def vertices_nearest(self, v: int, top_n: int = 10) -> List[Tuple[int, float]]:
        return self.sv.nearest(v, top_n)
