"""deeplearning4j_tpu — a TPU-native deep-learning framework with the
capability surface of Deeplearning4j 0.9.x, redesigned for JAX/XLA/Pallas.

Architecture (vs the reference's layer map, SURVEY.md §1):
- L0/L1 (DataVec/ND4J)   -> ``data/`` iterators + ``ops/`` on jax.numpy/XLA
- L2 (cuDNN helpers)     -> XLA fusion + ``runtime/`` Pallas kernels
- L3 (nn model)          -> ``nn/`` config-as-data layers + Sequential/Graph
- L4 (training loop)     -> ``train/`` jitted steps, listeners, early stopping
- L5 (scaleout)          -> ``parallel/`` Mesh + pjit/shard_map collectives
- L6 (import/UI)         -> ``keras_import/``, ``train/listeners`` stats
- L7 (apps)              -> ``models/`` zoo, ``nlp/``, ``graph/``, ``knn/``
"""

__version__ = "0.1.0"

from . import ops

__all__ = ["ops"]
