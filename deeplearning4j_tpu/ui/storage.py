"""Stats storage — parity with the reference StatsStorage stack
(``api/storage/StatsStorage.java`` in deeplearning4j-core, implementations in
``deeplearning4j-ui-model/ui/storage/``).

The reference persists SBE-encoded binary reports into MapDB/SQLite and
exposes a pub/sub listener API the UI server subscribes to. Here records are
JSON dicts keyed the same way — (session_id, type_id, worker_id, timestamp) —
with an in-memory impl and a stdlib-sqlite3 impl (J7FileStatsStorage parity).
JSON replaces SBE: stats records are small and off the training hot path, so
wire compactness buys nothing on a TPU host.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple


class StatsStorageEvent:
    def __init__(self, kind: str, session_id: str, type_id: str, worker_id: str,
                 timestamp: float):
        self.kind = kind  # new_session | new_worker | post_static | post_update
        self.session_id = session_id
        self.type_id = type_id
        self.worker_id = worker_id
        self.timestamp = timestamp


class BaseStatsStorage:
    """StatsStorage + StatsStorageRouter surface: put static/update records,
    enumerate sessions/workers, subscribe to change events."""

    def __init__(self):
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []
        self._lock = threading.Lock()

    # --- router (write) side ---
    def put_static_info(self, session_id: str, type_id: str, worker_id: str,
                        record: dict) -> None:
        first = self._store_static(session_id, type_id, worker_id, record)
        self._emit(StatsStorageEvent("new_session" if first else "post_static",
                                     session_id, type_id, worker_id, time.time()))

    def put_update(self, session_id: str, type_id: str, worker_id: str,
                   timestamp: float, record: dict) -> None:
        self._store_update(session_id, type_id, worker_id, timestamp, record)
        self._emit(StatsStorageEvent("post_update", session_id, type_id,
                                     worker_id, timestamp))

    # --- read side ---
    def list_sessions(self) -> List[str]:
        raise NotImplementedError

    def list_workers(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str, worker_id: str) -> Optional[dict]:
        raise NotImplementedError

    def get_updates(self, session_id: str, worker_id: str,
                    since: float = 0.0) -> List[Tuple[float, dict]]:
        raise NotImplementedError

    def get_updates_desc(self, session_id: str, worker_id: str,
                         limit: int = 50) -> List[dict]:
        """Most-recent-first records, bounded — lets readers find the latest
        detailed report without parsing the whole history."""
        raise NotImplementedError

    def latest_update(self, session_id: str, worker_id: str) -> Optional[dict]:
        ups = self.get_updates_desc(session_id, worker_id, limit=1)
        return ups[0] if ups else None

    # --- pub/sub ---
    def register_listener(self, fn: Callable[[StatsStorageEvent], None]) -> None:
        self._listeners.append(fn)

    def _emit(self, ev: StatsStorageEvent) -> None:
        for fn in list(self._listeners):
            fn(ev)

    def close(self) -> None:
        pass


class InMemoryStatsStorage(BaseStatsStorage):
    """``ui/storage/InMemoryStatsStorage.java``."""

    def __init__(self):
        super().__init__()
        self._static: Dict[Tuple[str, str], dict] = {}
        self._updates: Dict[Tuple[str, str], List[Tuple[float, dict]]] = \
            defaultdict(list)
        self._sessions: List[str] = []

    def _store_static(self, sid, tid, wid, record) -> bool:
        with self._lock:
            first = sid not in self._sessions
            if first:
                self._sessions.append(sid)
            # record stored verbatim (no injected keys) — keeps the two
            # storage backends byte-identical for the same puts
            self._static[(sid, wid)] = dict(record)
            return first

    def _store_update(self, sid, tid, wid, ts, record):
        with self._lock:
            if sid not in self._sessions:
                self._sessions.append(sid)
            self._updates[(sid, wid)].append((ts, record))

    def list_sessions(self):
        with self._lock:
            return list(self._sessions)

    def list_workers(self, session_id):
        with self._lock:
            return sorted({w for (s, w) in
                           set(self._static) | set(self._updates) if s == session_id})

    def get_static_info(self, session_id, worker_id):
        with self._lock:
            return self._static.get((session_id, worker_id))

    def get_updates(self, session_id, worker_id, since=0.0):
        with self._lock:
            return [(t, r) for t, r in self._updates.get((session_id, worker_id), [])
                    if t >= since]

    def get_updates_desc(self, session_id, worker_id, limit=50):
        with self._lock:
            ups = self._updates.get((session_id, worker_id), [])
            # appended ~in timestamp order: tail slice is O(limit), then a
            # small sort corrects any out-of-order remote-receiver stamps
            tail = ups[-limit:]
            return [r for _, r in sorted(tail, key=lambda p: -p[0])]


class FileStatsStorage(BaseStatsStorage):
    """``ui/storage/sqlite/J7FileStatsStorage.java`` — sqlite3-backed,
    survives process restarts; safe for one writer + many readers."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock, self._conn:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS static_info ("
                "session_id TEXT, type_id TEXT, worker_id TEXT, record TEXT, "
                "PRIMARY KEY (session_id, worker_id))")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS updates ("
                "session_id TEXT, type_id TEXT, worker_id TEXT, "
                "timestamp REAL, record TEXT)")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_updates ON updates "
                "(session_id, worker_id, timestamp)")

    def _store_static(self, sid, tid, wid, record) -> bool:
        with self._lock, self._conn:
            # "first" means never seen in EITHER table (matches InMemory: an
            # update-only session is already known, so no new_session event)
            cur = self._conn.execute(
                "SELECT 1 FROM static_info WHERE session_id=? "
                "UNION SELECT 1 FROM updates WHERE session_id=? LIMIT 1",
                (sid, sid))
            first = cur.fetchone() is None
            self._conn.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?,?,?,?)",
                (sid, tid, wid, json.dumps(record)))
            return first

    def _store_update(self, sid, tid, wid, ts, record):
        with self._lock, self._conn:
            self._conn.execute("INSERT INTO updates VALUES (?,?,?,?,?)",
                               (sid, tid, wid, ts, json.dumps(record)))

    def list_sessions(self):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT session_id FROM static_info "
                "UNION SELECT DISTINCT session_id FROM updates").fetchall()
            return [r[0] for r in rows]

    def list_workers(self, session_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT worker_id FROM updates WHERE session_id=? "
                "UNION SELECT DISTINCT worker_id FROM static_info "
                "WHERE session_id=?", (session_id, session_id)).fetchall()
            return sorted(r[0] for r in rows)

    def get_static_info(self, session_id, worker_id):
        with self._lock:
            row = self._conn.execute(
                "SELECT record FROM static_info WHERE session_id=? AND worker_id=?",
                (session_id, worker_id)).fetchone()
            return json.loads(row[0]) if row else None

    def get_updates(self, session_id, worker_id, since=0.0):
        with self._lock:
            rows = self._conn.execute(
                "SELECT timestamp, record FROM updates WHERE session_id=? AND "
                "worker_id=? AND timestamp>=? ORDER BY timestamp",
                (session_id, worker_id, since)).fetchall()
            return [(t, json.loads(r)) for t, r in rows]

    def get_updates_desc(self, session_id, worker_id, limit=50):
        with self._lock:
            rows = self._conn.execute(
                "SELECT record FROM updates WHERE session_id=? AND worker_id=? "
                "ORDER BY timestamp DESC LIMIT ?",
                (session_id, worker_id, limit)).fetchall()
            return [json.loads(r[0]) for r in rows]

    def close(self):
        with self._lock:
            self._conn.close()
