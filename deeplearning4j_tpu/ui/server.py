"""Training UI server — ``deeplearning4j-play/.../PlayUIServer.java`` +
``module/train/TrainModule.java`` + ``module/remote/RemoteReceiverModule.java``
equivalent on stdlib ``http.server`` (no Play framework, no extra deps).

Endpoints:
- GET  /                              — dashboard (inline HTML+SVG charts:
  per-worker score curve, iteration timing, layer param tables)
- GET  /train/sessions                — JSON session ids
- GET  /train/<sid>/overview?since=T  — per-worker score + timing series,
  incremental (only records with timestamp >= T)
- GET  /train/<sid>/model             — static info + latest per-layer stats
- GET  /metrics                       — Prometheus scrape (request latency
  histograms per endpoint; see obs/)
- POST /remote                        — remote stats receiver: JSON
  {"kind": "static"|"update", "session_id", "worker_id", ...} pushed from
  other processes/hosts (VanillaStatsStorageRouter → RemoteReceiverModule)
"""

from __future__ import annotations

import json
from typing import Optional
from urllib.parse import parse_qs, unquote, urlparse

from ..obs.metrics import MetricsRegistry
from ..utils.httpd import JsonHTTPServerMixin, JsonRequestHandler
from .storage import BaseStatsStorage, InMemoryStatsStorage

_DASH_HTML = """<!DOCTYPE html>
<html><head><title>Training UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
.chart{background:#fff;border:1px solid #ddd;margin:10px;padding:10px;display:inline-block}
h3,h4{margin:4px}
</style></head>
<body>
<h2>Training sessions</h2><div id="root"></div>
<script>
async function j(u){const r=await fetch(u);return r.json()}
function esc(s){return String(s).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function poly(xs,ys,w,h,color){
  if(ys.length<2)return '<i>collecting…</i>';
  const xmin=Math.min(...xs),xmax=Math.max(...xs),ymin=Math.min(...ys),ymax=Math.max(...ys);
  const sx=x=>(x-xmin)/Math.max(xmax-xmin,1e-9)*(w-40)+30;
  const sy=y=>h-20-(y-ymin)/Math.max(ymax-ymin,1e-9)*(h-40);
  const pts=xs.map((x,i)=>sx(x)+','+sy(ys[i])).join(' ');
  return `<svg width=${w} height=${h}><polyline fill=none stroke=${color} stroke-width=1.5 points="${pts}"/>`+
    `<text x=2 y=12 font-size=10>${ymax.toPrecision(4)}</text><text x=2 y=${h-8} font-size=10>${ymin.toPrecision(4)}</text></svg>`;
}
const state={};  // sid -> wid -> {since, iters, scores, ms} incremental caches
async function render(){
  const sessions=await j('/train/sessions');const root=document.getElementById('root');
  let html='';
  for(const sid of sessions){
    if(!state[sid])state[sid]={};
    const ws=state[sid];
    const mins=Object.values(ws).map(w=>w.since);
    const since=mins.length?Math.min(...mins):0;
    const ov=await j('/train/'+encodeURIComponent(sid)+'/overview?since='+since);
    for(const[wid,series]of Object.entries(ov.workers)){
      if(!ws[wid])ws[wid]={since:0,iters:[],scores:[],ms:[]};
      const st=ws[wid];
      series.timestamps.forEach((t,i)=>{
        if(t>st.since){st.iters.push(series.iterations[i]);
          st.scores.push(series.scores[i]);st.ms.push(series.iteration_ms[i]);}
      });
      if(series.timestamps.length)
        st.since=Math.max(st.since,series.timestamps[series.timestamps.length-1]);
    }
    html+=`<h3>${esc(sid)}</h3>`;
    for(const[wid,st]of Object.entries(ws)){
      html+=`<div class=chart><h4>${esc(wid)} score</h4>${poly(st.iters,st.scores,420,180,'#d62728')}</div>`;
      const it=st.iters.filter((_,i)=>st.ms[i]!=null), ms=st.ms.filter(v=>v!=null);
      if(ms.length>1)
        html+=`<div class=chart><h4>${esc(wid)} iteration ms</h4>${poly(it,ms,420,180,'#1f77b4')}</div>`;
    }
    const model=await j('/train/'+encodeURIComponent(sid)+'/model');
    if(model.latest&&model.latest.params){
      html+=`<div class=chart><h4>param mean magnitude (latest)</h4><table border=0>`;
      for(const[k,v]of Object.entries(model.latest.params)){
        const mm=v.mean_magnitude==null?'n/a (non-finite)':v.mean_magnitude.toExponential(3);
        html+=`<tr><td>${esc(k)}</td><td>${esc(mm)}</td></tr>`;
      }
      html+='</table></div>';
      html+=histsection('param histograms',model.latest.params);
      if(model.latest.updates&&Object.keys(model.latest.updates).length)
        html+=histsection('update histograms',model.latest.updates);
      if(model.latest.activations)
        html+=histsection('activation histograms (probe batch)',model.latest.activations);
      if(model.latest.conv_filters)html+=filters(model.latest.conv_filters);
    }
  }
  root.innerHTML=html||'<i>no sessions yet</i>';
}
function bars(h,w,ht){
  if(!h||!h.counts||!h.counts.length)return '';
  const mx=Math.max(...h.counts,1);const bw=(w-10)/h.counts.length;
  let s=`<svg width=${w} height=${ht}>`;
  h.counts.forEach((c,i)=>{const bh=c/mx*(ht-22);
    s+=`<rect x=${5+i*bw} y=${ht-16-bh} width=${Math.max(bw-1,1)} height=${bh} fill=#2ca02c />`});
  s+=`<text x=2 y=${ht-3} font-size=9>${h.min.toPrecision(3)}</text>`+
     `<text x=${w-48} y=${ht-3} font-size=9>${h.max.toPrecision(3)}</text></svg>`;
  return s;
}
function histsection(title,stats){
  let s=`<div class=chart><h4>${esc(title)}</h4>`;
  for(const[k,v]of Object.entries(stats)){
    if(!v.histogram)continue;
    s+=`<div style="display:inline-block;margin:3px"><div style="font-size:11px">${esc(k)}</div>${bars(v.histogram,170,90)}</div>`;
  }
  return s+'</div>';
}
function filters(f){
  const cell=8;let s=`<div class=chart><h4>conv filters: ${esc(f.layer)}</h4>`;
  for(const g of f.filters){
    s+=`<svg width=${f.kw*cell+2} height=${f.kh*cell+2} style="margin:2px;border:1px solid #ccc">`;
    g.forEach((row,y)=>row.forEach((v,x)=>{
      s+=`<rect x=${x*cell} y=${y*cell} width=${cell} height=${cell} fill=rgb(${v},${v},${v}) />`}));
    s+='</svg>';
  }
  return s+'</div>';
}
render();setInterval(render,5000);
</script></body></html>"""

_TSNE_HTML = """<!DOCTYPE html>
<html><head><title>t-SNE viewer</title>
<style>body{font-family:sans-serif;margin:20px}</style></head>
<body><h2>t-SNE embedding</h2><div id="plot"><i>no embedding uploaded</i></div>
<script>
const PALETTE=['#1f77b4','#ff7f0e','#2ca02c','#d62728','#9467bd','#8c564b',
               '#e377c2','#7f7f7f','#bcbd22','#17becf'];
function esc(s){return String(s).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
async function render(){
  const r=await fetch('/tsne/data');const d=await r.json();
  if(!d.coords||!d.coords.length)return;
  const W=760,H=560,pad=30;
  const xs=d.coords.map(c=>c[0]),ys=d.coords.map(c=>c[1]);
  const xmin=Math.min(...xs),xmax=Math.max(...xs),ymin=Math.min(...ys),ymax=Math.max(...ys);
  const sx=x=>(x-xmin)/Math.max(xmax-xmin,1e-9)*(W-2*pad)+pad;
  const sy=y=>H-pad-(y-ymin)/Math.max(ymax-ymin,1e-9)*(H-2*pad);
  let s=`<svg width=${W} height=${H} style="border:1px solid #ddd">`;
  d.coords.forEach((c,i)=>{
    const lab=d.labels?d.labels[i]:0;
    const col=typeof lab==='number'?PALETTE[lab%10]:PALETTE[Math.abs(String(lab).split('').reduce((a,ch)=>a+ch.charCodeAt(0),0))%10];
    s+=`<circle cx=${sx(c[0])} cy=${sy(c[1])} r=2.5 fill=${col}><title>${esc(lab)}</title></circle>`;
  });
  document.getElementById('plot').innerHTML=s+'</svg>';
}
render();setInterval(render,5000);
</script></body></html>"""


class UIServer(JsonHTTPServerMixin):
    """``UIServer.getInstance().attach(storage)`` parity."""

    def __init__(self, storage: Optional[BaseStatsStorage] = None, port: int = 9001,
                 host: str = "127.0.0.1", metrics: MetricsRegistry = None):
        self.storage = storage or InMemoryStatsStorage()
        self.port = port
        self.host = host  # bind 0.0.0.0 for the cross-host remote-receiver path
        # per-endpoint latency + GET /metrics, provided by the httpd layer
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._tsne: dict = {}  # {"coords": [[x,y],...], "labels": [...]}

    @staticmethod
    def _metric_route(path: str) -> str:
        """Collapse session-parameterized paths so the endpoint label stays
        bounded-cardinality no matter how many sessions exist."""
        parts = path.split("/")
        if len(parts) == 4 and parts[1] == "train" and \
                parts[3] in ("overview", "model"):
            return f"/train/{{sid}}/{parts[3]}"
        return path

    def upload_tsne(self, coords, labels=None) -> "UIServer":
        """Publish a 2-D embedding to the /tsne viewer (TsneModule parity:
        the reference uploads t-SNE coord files to the UI)."""
        import numpy as _np

        c = _np.asarray(coords, float)
        if c.ndim != 2 or c.shape[1] < 2:
            raise ValueError(f"coords must be (N, 2+), got {c.shape}")
        self._tsne = {"coords": c[:, :2].tolist(),
                      "labels": list(labels) if labels is not None else None}
        return self

    def attach(self, storage: BaseStatsStorage) -> "UIServer":
        self.storage = storage
        return self

    def _overview(self, sid: str, since: float = 0.0) -> dict:
        """Per-worker score/timing series (workers are separate runs and must
        not be interleaved into one line); ``since`` keeps polling O(new)."""
        workers = {}
        for wid in self.storage.list_workers(sid):
            ts, iters, scores, ms = [], [], [], []
            for t, rec in self.storage.get_updates(sid, wid, since=since):
                if "score" in rec:
                    ts.append(t)
                    iters.append(rec.get("iteration", len(iters)))
                    scores.append(rec["score"])
                    ms.append(rec.get("iteration_ms"))
            workers[wid] = {"timestamps": ts, "iterations": iters,
                            "scores": scores, "iteration_ms": ms,
                            "last_timestamp": ts[-1] if ts else None}
        return {"workers": workers}

    def _model(self, sid: str) -> dict:
        workers = self.storage.list_workers(sid)
        static = self.storage.get_static_info(sid, workers[0]) if workers else None
        latest = None
        for wid in workers:
            for rec in self.storage.get_updates_desc(sid, wid, limit=50):
                if "params" in rec:
                    latest = rec
                    break
            if latest:
                break
        return {"static": static, "latest": latest}

    def _handler(self):
        server = self

        class Handler(JsonRequestHandler):
            owner = server

            def do_GET(self):
                parsed = urlparse(self.path)
                path = parsed.path
                try:
                    if path in ("/", "/train", "/train/"):
                        self.reply(200, _DASH_HTML, "text/html")
                    elif path in ("/tsne", "/tsne/"):
                        self.reply(200, _TSNE_HTML, "text/html")
                    elif path == "/tsne/data":
                        self.reply(200, server._tsne or {"coords": [], "labels": None})
                    elif path == "/train/sessions":
                        self.reply(200, server.storage.list_sessions())
                    elif path.startswith("/train/") and path.endswith("/overview"):
                        sid = unquote(path.split("/")[2])
                        qs = parse_qs(parsed.query)
                        since = float(qs.get("since", ["0"])[0])
                        self.reply(200, server._overview(sid, since))
                    elif path.startswith("/train/") and path.endswith("/model"):
                        sid = unquote(path.split("/")[2])
                        self.reply(200, server._model(sid))
                    else:
                        self.reply(404, {"error": "unknown endpoint"})
                except (KeyError, ValueError, TypeError, AttributeError) as e:
                    self.reply(400, {"error": str(e)})
                except Exception as e:  # server must answer every request  # jaxlint: disable=broad-except
                    self.reply(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):
                path = urlparse(self.path).path
                try:
                    req = self.read_json()
                    if path == "/remote":
                        kind = req.get("kind", "update")
                        sid = req["session_id"]
                        wid = req.get("worker_id", "remote_0")
                        tid = req.get("type_id", "StatsListener")
                        if kind == "static":
                            server.storage.put_static_info(sid, tid, wid,
                                                           req.get("record", {}))
                        else:
                            server.storage.put_update(
                                sid, tid, wid, float(req.get("timestamp", 0.0)),
                                req.get("record", {}))
                        self.reply(200, {"status": "ok"})
                    elif path == "/tsne/upload":
                        server.upload_tsne(req["coords"], req.get("labels"))
                        self.reply(200, {"status": "ok",
                                         "points": len(server._tsne["coords"])})
                    else:
                        self.reply(404, {"error": "unknown endpoint"})
                except (KeyError, ValueError, TypeError, AttributeError,
                        json.JSONDecodeError) as e:
                    self.reply(400, {"error": str(e)})
                except Exception as e:  # server must answer every request  # jaxlint: disable=broad-except
                    self.reply(500, {"error": f"{type(e).__name__}: {e}"})

        return Handler


class RemoteStatsRouter:
    """Client-side router that pushes stats to a remote UIServer /remote
    endpoint — ``impl/listeners/VanillaStatsStorageRouter`` + remote receiver
    parity. Implements the same put_* surface as BaseStatsStorage so
    StatsListener can write straight to a remote dashboard."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9001,
                 timeout: float = 5.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _post(self, payload: dict):
        import urllib.request

        req = urllib.request.Request(
            self.base + "/remote", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def put_static_info(self, session_id, type_id, worker_id, record):
        self._post({"kind": "static", "session_id": session_id,
                    "type_id": type_id, "worker_id": worker_id, "record": record})

    def put_update(self, session_id, type_id, worker_id, timestamp, record):
        self._post({"kind": "update", "session_id": session_id,
                    "type_id": type_id, "worker_id": worker_id,
                    "timestamp": timestamp, "record": record})
