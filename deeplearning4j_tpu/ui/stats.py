"""StatsListener — ``ui/stats/BaseStatsListener.java`` (783 LoC) equivalent.

Collects per-iteration score + timing and (each ``frequency`` iterations)
per-layer parameter/update statistics — mean magnitude, stddev, histogram —
plus host memory and device info. Records go to a ``BaseStatsStorage`` via
the router API, which the dashboard server subscribes to.

TPU redesign: DL4J hooks onGradientCalculation inside its backprop loop.
Our train step is one fused XLA program, so gradients aren't observable
mid-step; updates are recovered from param deltas between reports, normalized
to mean per-step magnitude (each entry records ``averaged_over_iterations``).
The stats math runs as a jitted reduction per tensor — device programs per
report, not one JNI call per layer per iteration like the reference.
"""

from __future__ import annotations

import json
import os
import platform
import time
import uuid
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..train.listeners import TrainingListener
from .storage import BaseStatsStorage

_HIST_BINS = 20


def _flatten_names(tree, prefix="") -> Dict[str, jnp.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_names(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def _histogram(x: np.ndarray) -> dict:
    finite = x[np.isfinite(x)]
    nonfinite = int(x.size - finite.size)
    if finite.size == 0:
        # diverged tensor: report an empty histogram instead of crashing the
        # training loop from inside the monitoring path
        return {"counts": [0] * _HIST_BINS, "min": 0.0, "max": 0.0,
                "nonfinite": nonfinite}
    counts, edges = np.histogram(finite, bins=_HIST_BINS)
    out = {"counts": counts.tolist(), "min": float(edges[0]),
           "max": float(edges[-1])}
    if nonfinite:
        out["nonfinite"] = nonfinite
    return out


class StatsListener(TrainingListener):
    """Attach to ``Trainer.fit(listeners=[...])``; routes stats into storage.

    Parity knobs (StatsUpdateConfiguration): collect histograms / mean
    magnitudes for params and updates, reporting frequency.
    """

    def __init__(self, storage: BaseStatsStorage, session_id: Optional[str] = None,
                 worker_id: str = "worker_0", frequency: int = 10,
                 collect_histograms: bool = True,
                 activation_probe=None, collect_conv_filters: bool = True):
        self.storage = storage
        self.session_id = session_id or f"session_{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.frequency = max(int(frequency), 1)
        self.collect_histograms = collect_histograms
        # fixed probe batch for per-layer activation stats (TrainModule's
        # activations tab; DL4J hooks the live forward — our step is one
        # fused program, so a probe forward at report time replaces it)
        self.activation_probe = activation_probe
        self.collect_conv_filters = collect_conv_filters
        self._prev_params = None
        self._last_time = None
        self._initialized = False

    # --- static (once): system/model info (BaseStatsListener initial report) ---
    def _post_static(self, trainer):
        devs = jax.devices()
        model = trainer.model
        record = {
            "software": {
                "python": platform.python_version(),
                "jax": jax.__version__,
                "backend": devs[0].platform if devs else "unknown",
                "hostname": platform.node(),
                "pid": os.getpid(),
            },
            "hardware": {
                "device_count": len(devs),
                "devices": [str(d) for d in devs],
                "cpu_count": os.cpu_count(),
            },
            "model": {
                "class": type(model).__name__,
                "param_count": int(model.param_count()),
                "config": json.loads(model.to_json()),
            },
            "start_time": time.time(),
        }
        self.storage.put_static_info(self.session_id, "StatsListener",
                                     self.worker_id, record)
        self._initialized = True

    def iteration_done(self, trainer, iteration: int, epoch: int, loss: float):
        if not self._initialized:
            self._post_static(trainer)
        now = time.time()
        record = {
            "iteration": iteration,
            "epoch": epoch,
            "score": float(loss),
            "iteration_ms": None if self._last_time is None
            else (now - self._last_time) * 1e3,
        }
        self._last_time = now
        if iteration % self.frequency == 0:
            self._cur_iteration = iteration
            record.update(self._detail_stats(trainer))
        self.storage.put_update(self.session_id, "StatsListener",
                                self.worker_id, now, record)

    def _detail_stats(self, trainer) -> dict:
        params = trainer.params
        flat = _flatten_names(params)
        param_stats = {}
        for name, leaf in flat.items():
            mm, sd, mn, mx = (_finite_or_none(v) for v in jax.tree.leaves(_stat4(leaf)))
            entry = {"mean_magnitude": mm, "std": sd, "min": mn, "max": mx}
            if self.collect_histograms:
                entry["histogram"] = _histogram(np.asarray(leaf).ravel())
            param_stats[name] = entry
        update_stats = {}
        if self._prev_params is not None:
            prev, prev_iter = self._prev_params
            gap = max(self._cur_iteration - prev_iter, 1)
            # delta spans `gap` iterations; normalize so the reported numbers
            # are MEAN PER-STEP update magnitudes regardless of frequency
            upd = jax.tree.map(lambda a, b: (np.asarray(a) - b) / gap, params, prev)
            for name, leaf in _flatten_names(upd).items():
                mm, sd, mn, mx = (_finite_or_none(v)
                                  for v in jax.tree.leaves(_stat4(leaf)))
                entry = {"mean_magnitude": mm, "std": sd, "min": mn, "max": mx,
                         "averaged_over_iterations": gap}
                if self.collect_histograms:
                    entry["histogram"] = _histogram(np.asarray(leaf).ravel())
                update_stats[name] = entry
        # snapshot to host numpy: the trainer's jitted step DONATES the param
        # buffers, so holding device arrays across iterations would leave
        # deleted arrays in our hands
        self._prev_params = (jax.tree.map(np.asarray, params), self._cur_iteration)
        mem = {}
        try:
            import resource

            mem["max_rss_mb"] = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0
        except ImportError:  # non-POSIX
            pass
        out = {"params": param_stats, "updates": update_stats, "memory": mem}
        act = self._activation_stats(trainer)
        if act:
            out["activations"] = act
        if self.collect_conv_filters:
            filt = conv_filter_grid(params)
            if filt:
                out["conv_filters"] = filt
        return out

    def _activation_stats(self, trainer) -> dict:
        """Per-layer activation mean/std/histogram on the probe batch
        (TrainModule activations view)."""
        if self.activation_probe is None:
            return {}
        model = trainer.model
        if not hasattr(model, "activations"):
            return {}
        acts = model.activations(trainer.params, trainer.state,
                                 jnp.asarray(self.activation_probe))
        out = {}
        for i, a in enumerate(acts):
            mm, sd, mn, mx = (_finite_or_none(v)
                              for v in jax.tree.leaves(_stat4(a)))
            an = np.asarray(a)
            entry = {"mean_magnitude": mm, "std": sd, "min": mn, "max": mx,
                     "shape": list(an.shape)}
            if self.collect_histograms:
                entry["histogram"] = _histogram(an.ravel())
            out[f"layer_{i}"] = entry
        return out


def _layer_sort_key(name: str):
    """Numeric-aware ordering so layer_10 sorts after layer_2."""
    import re

    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", name)]


def conv_filter_grid(params, max_filters: int = 16) -> Optional[dict]:
    """First conv layer's kernels as a JSON-safe grid of 0..255 ints
    (TrainModule's convolutional filter visualization). Kernels are HWIO;
    input channels are averaged, each filter min-max normalized."""
    flat = _flatten_names(params)
    for lname in sorted(flat, key=_layer_sort_key):
        if not lname.endswith("/w"):
            continue
        w = np.asarray(flat[lname])
        if w.ndim != 4:  # (kh, kw, cin, cout) convs only
            continue
        kh, kw, _, cout = w.shape
        n = min(cout, max_filters)
        grid = []
        for f in range(n):
            k = w[:, :, :, f].mean(axis=-1)
            lo, hi = float(k.min()), float(k.max())
            norm = (k - lo) / (hi - lo) if hi > lo else np.zeros_like(k)
            grid.append(np.round(norm * 255).astype(int).tolist())
        return {"layer": lname, "kh": kh, "kw": kw, "filters": grid}
    return None


@jax.jit
def _stat4(x):
    return (jnp.mean(jnp.abs(x)), jnp.std(x), jnp.min(x), jnp.max(x))


def _finite_or_none(v) -> Optional[float]:
    """NaN/inf → None: keeps the stored records strict-JSON (browser fetch()
    rejects bare NaN) while still flagging divergence to the dashboard."""
    f = float(v)
    return f if np.isfinite(f) else None
