"""Training UI & stats — deeplearning4j-ui-parent equivalent (SURVEY.md §2.6):
StatsListener collection → StatsStorage (memory / sqlite file) → web dashboard
with a remote-receiver endpoint for cluster jobs."""

from .stats import StatsListener
from .storage import BaseStatsStorage, FileStatsStorage, InMemoryStatsStorage
from .server import RemoteStatsRouter, UIServer

__all__ = ["BaseStatsStorage", "FileStatsStorage", "InMemoryStatsStorage",
           "RemoteStatsRouter", "StatsListener", "UIServer"]
