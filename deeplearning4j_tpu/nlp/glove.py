"""GloVe — parity with ``models/glove/`` (``AbstractCoOccurrences.java`` 646
LoC co-occurrence counting + ``learning/impl/elements/GloVe.java`` AdaGrad
training).

TPU-first: the sparse co-occurrence matrix is flattened to COO index/value
arrays; training is one jitted AdaGrad step over shuffled batches of entries
— weighted least squares  f(X_ij) (w_i·w~_j + b_i + b~_j − log X_ij)² on the
MXU with scatter-add updates, exactly the GloVe paper objective the reference
implements per-pair.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .tokenization import DefaultTokenizerFactory, TokenizerFactory
from .vocab import VocabCache, VocabConstructor


class CoOccurrences:
    """``AbstractCoOccurrences.java`` — symmetric windowed co-occurrence
    counts weighted by 1/distance."""

    def __init__(self, vocab: VocabCache, window: int = 5, symmetric: bool = True):
        self.vocab = vocab
        self.window = window
        self.symmetric = symmetric
        self.counts: Dict[Tuple[int, int], float] = defaultdict(float)

    def fit(self, token_lists: Iterable[Sequence[str]]) -> "CoOccurrences":
        for toks in token_lists:
            idx = [self.vocab.index_of(t) for t in toks]
            idx = [i for i in idx if i >= 0]
            for i, wi in enumerate(idx):
                for off in range(1, self.window + 1):
                    j = i + off
                    if j >= len(idx):
                        break
                    w = 1.0 / off
                    self.counts[(wi, idx[j])] += w
                    if self.symmetric:
                        self.counts[(idx[j], wi)] += w
        return self

    def coo(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not self.counts:
            return (np.zeros(0, np.int32),) * 2 + (np.zeros(0, np.float32),)
        ij = np.array(list(self.counts.keys()), dtype=np.int32)
        x = np.array(list(self.counts.values()), dtype=np.float32)
        return ij[:, 0], ij[:, 1], x


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(W, Wt, b, bt, gW, gWt, gb, gbt, rows, cols, logx, fx, lr):
    """One AdaGrad batch over COO entries (GloVe.java per-pair math, batched)."""
    wi, wj = W[rows], Wt[cols]                           # (B, D)
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bt[cols] - logx
    wdiff = fx * diff                                    # (B,)
    loss = 0.5 * jnp.mean(fx * diff * diff)
    g_wi = wdiff[:, None] * wj
    g_wj = wdiff[:, None] * wi
    # AdaGrad: accumulate squared grads, scale update
    gW = gW.at[rows].add(g_wi ** 2)
    gWt = gWt.at[cols].add(g_wj ** 2)
    gb = gb.at[rows].add(wdiff ** 2)
    gbt = gbt.at[cols].add(wdiff ** 2)
    W = W.at[rows].add(-lr * g_wi / jnp.sqrt(gW[rows] + 1e-8))
    Wt = Wt.at[cols].add(-lr * g_wj / jnp.sqrt(gWt[cols] + 1e-8))
    b = b.at[rows].add(-lr * wdiff / jnp.sqrt(gb[rows] + 1e-8))
    bt = bt.at[cols].add(-lr * wdiff / jnp.sqrt(gbt[cols] + 1e-8))
    return W, Wt, b, bt, gW, gWt, gb, gbt, loss


class Glove:
    """User-facing GloVe model (``models/glove/Glove.java`` builder surface:
    minWordFrequency, layerSize, windowSize, learningRate, xMax, alpha,
    epochs, batchSize, seed)."""

    def __init__(self, min_word_frequency: int = 1, layer_size: int = 50,
                 window_size: int = 5, learning_rate: float = 0.05,
                 x_max: float = 100.0, alpha: float = 0.75, epochs: int = 5,
                 batch_size: int = 4096, seed: int = 42,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.W: Optional[np.ndarray] = None

    def fit(self, sentences: Iterable[str]) -> List[float]:
        token_lists = [self.tokenizer.create(s).get_tokens() for s in sentences]
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False).build(token_lists)
        co = CoOccurrences(self.vocab, window=self.window_size).fit(token_lists)
        rows, cols, x = co.coo()
        if not len(x):
            self.W = np.zeros((len(self.vocab), self.layer_size), np.float32)
            return []
        logx = np.log(x)
        fx = np.minimum((x / self.x_max) ** self.alpha, 1.0).astype(np.float32)
        V, D = len(self.vocab), self.layer_size
        rng = np.random.default_rng(self.seed)
        W = jnp.asarray((rng.random((V, D), dtype=np.float32) - 0.5) / D)
        Wt = jnp.asarray((rng.random((V, D), dtype=np.float32) - 0.5) / D)
        b = jnp.zeros(V, jnp.float32); bt = jnp.zeros(V, jnp.float32)
        gW = jnp.full((V, D), 1e-8, jnp.float32); gWt = jnp.full((V, D), 1e-8, jnp.float32)
        gb = jnp.full(V, 1e-8, jnp.float32); gbt = jnp.full(V, 1e-8, jnp.float32)
        B = min(self.batch_size, len(x))
        losses = []
        for _ in range(self.epochs):
            order = rng.permutation(len(x))
            ep, nb = 0.0, 0
            for s in range(0, len(order) - B + 1, B):
                sel = order[s:s + B]
                W, Wt, b, bt, gW, gWt, gb, gbt, loss = _glove_step(
                    W, Wt, b, bt, gW, gWt, gb, gbt,
                    jnp.asarray(rows[sel]), jnp.asarray(cols[sel]),
                    jnp.asarray(logx[sel]), jnp.asarray(fx[sel]),
                    self.learning_rate)
                ep += float(loss); nb += 1
            losses.append(ep / max(nb, 1))
        # GloVe paper: final embedding = W + W~
        self.W = np.asarray(W) + np.asarray(Wt)
        return losses

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.W[i]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        den = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / den) if den > 0 else 0.0
