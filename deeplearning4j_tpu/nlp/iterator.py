"""CnnSentenceIterator — parity with
``iterator/CnnSentenceDataSetIterator.java`` (516 LoC): turns labelled
sentences + word vectors into fixed-shape (B, maxlen, dim) tensors + one-hot
labels + a sequence mask, ready for Convolution1D sentence classifiers.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .tokenization import (DefaultTokenizerFactory, LabelledDocument,
                           TokenizerFactory)


class CnnSentenceIterator:
    def __init__(self, docs: Sequence[LabelledDocument], word_vectors,
                 batch_size: int = 32, max_length: int = 64,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 shuffle_seed: Optional[int] = None):
        """``word_vectors``: any object with has_word(w) + get_word_vector(w)
        (e.g. Word2Vec) — mirrors the reference taking a WordVectors."""
        self.docs = list(docs)
        self.wv = word_vectors
        self.batch_size = batch_size
        self.max_length = max_length
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.labels = sorted({lab for d in self.docs for lab in d.labels})
        self._label_idx = {l: i for i, l in enumerate(self.labels)}
        self._rng = np.random.default_rng(shuffle_seed) if shuffle_seed is not None else None
        probe = self.wv.get_word_vector(next(
            w for d in self.docs for w in self.tokenizer.create(d.content).get_tokens()
            if self.wv.has_word(w)))
        self.dim = len(probe)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        order = np.arange(len(self.docs))
        if self._rng is not None:
            self._rng.shuffle(order)
        for s in range(0, len(order), self.batch_size):
            idx = order[s:s + self.batch_size]
            B = len(idx)
            x = np.zeros((B, self.max_length, self.dim), np.float32)
            y = np.zeros((B, len(self.labels)), np.float32)
            mask = np.zeros((B, self.max_length), np.float32)
            for r, di in enumerate(idx):
                d = self.docs[di]
                toks = [t for t in self.tokenizer.create(d.content).get_tokens()
                        if self.wv.has_word(t)][:self.max_length]
                for c, t in enumerate(toks):
                    x[r, c] = self.wv.get_word_vector(t)
                    mask[r, c] = 1.0
                for lab in d.labels:
                    y[r, self._label_idx[lab]] = 1.0
            yield x, y, mask
