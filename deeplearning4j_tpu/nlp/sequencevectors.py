"""SequenceVectors — the generic skip-gram/CBOW engine the reference builds
Word2Vec / ParagraphVectors / DeepWalk on (``models/sequencevectors/
SequenceVectors.java``, ``learning/impl/elements/{SkipGram,CBOW}.java``).

TPU-native redesign: the reference dispatches one native ``AggregateSkipGram``
/ ``AggregateCBOW`` op per (center, context) pair (CBOW.java:166). Here an
epoch is pre-sampled on the host into flat index arrays, then consumed in
large minibatches by ONE jitted update step:

    gather rows -> dot products (MXU) -> sigmoid objective
    -> manual per-row gradients -> scatter-add into the tables

Both negative sampling and hierarchical softmax are fixed-shape (padded codes
+ mask), so XLA compiles the whole inner loop once.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import VocabCache, VocabConstructor, huffman_tensors, unigram_table


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# --------------------------------------------------------------------------
# Jitted update steps. Tables: syn0 (input vectors, V x D), syn1 (output /
# inner-node vectors, V x D). Learning rate is a traced scalar so linear decay
# (SequenceVectors alpha -> minAlpha) re-uses the compiled program.
#
# Every scatter-add is multiplicity-normalized (1/sqrt(count) per row): a
# natural (Zipfian) corpus puts a high-frequency word ("the") in hundreds of
# rows of one batch; summing all those gradients into one table row at
# word2vec learning rates diverges to inf. Rows that appear once (the common
# case at large vocab) are untouched.
# --------------------------------------------------------------------------

def _row_scale(n_rows, idx, *more_idx):
    """sqrt(multiplicity) divisors for scatter rows ``idx`` (counts pooled
    across all index arrays that target the same table). sqrt — not full
    1/count — keeps frequent rows learning proportionally to sqrt(freq)
    (SGD noise-averaging scale) while bounding the summed-update blowup."""
    c = jnp.zeros(n_rows, jnp.float32).at[idx].add(1.0)
    for m in more_idx:
        c = c.at[m].add(1.0)
    return jnp.sqrt(jnp.maximum(c, 1.0))


def _skipgram_ns_math(syn0, syn1, centers, contexts, negatives, lr):
    """Skip-gram + negative sampling. centers/contexts: (B,), negatives: (B,K)."""
    v_in = syn0[centers]                       # (B, D)
    v_pos = syn1[contexts]                     # (B, D)
    v_neg = syn1[negatives]                    # (B, K, D)
    pos_score = jnp.einsum("bd,bd->b", v_in, v_pos)
    neg_score = jnp.einsum("bd,bkd->bk", v_in, v_neg)
    # loss = -log s(pos) - sum log s(-neg)
    loss = -jnp.mean(jax.nn.log_sigmoid(pos_score)) \
           - jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg_score), axis=1))
    g_pos = _sigmoid(pos_score) - 1.0          # dL/d(pos_score), per example
    g_neg = _sigmoid(neg_score)                # (B, K)
    grad_in = g_pos[:, None] * v_pos + jnp.einsum("bk,bkd->bd", g_neg, v_neg)
    c_in = _row_scale(syn0.shape[0], centers)
    grad_in = grad_in / c_in[centers][:, None]
    grad_pos = g_pos[:, None] * v_in
    grad_neg = g_neg[..., None] * v_in[:, None, :]
    neg_flat = negatives.reshape(-1)
    c_out = _row_scale(syn1.shape[0], contexts, neg_flat)
    grad_pos = grad_pos / c_out[contexts][:, None]
    grad_neg_flat = grad_neg.reshape(-1, grad_neg.shape[-1]) \
        / c_out[neg_flat][:, None]
    syn0 = syn0.at[centers].add(-lr * grad_in)
    syn1 = syn1.at[contexts].add(-lr * grad_pos)
    syn1 = syn1.at[neg_flat].add(-lr * grad_neg_flat)
    return syn0, syn1, loss


_skipgram_ns_step = functools.partial(jax.jit, donate_argnums=(0, 1))(_skipgram_ns_math)


def _skipgram_hs_math(syn0, syn1, centers, codes, points, mask, lr):
    """Skip-gram + hierarchical softmax. codes/points/mask: (B, L) along the
    context word's Huffman path (padded). Inner nodes near the Huffman root
    appear on nearly every path, so path-row updates are count-normalized
    (masked slots excluded from the counts)."""
    v_in = syn0[centers]                       # (B, D)
    v_path = syn1[points]                      # (B, L, D)
    score = jnp.einsum("bd,bld->bl", v_in, v_path)
    sign = 1.0 - 2.0 * codes.astype(jnp.float32)      # code 0 -> +1, 1 -> -1
    loss = -jnp.sum(jax.nn.log_sigmoid(sign * score) * mask) / jnp.maximum(mask.sum(), 1.0)
    g = (_sigmoid(score) - (1.0 - codes.astype(jnp.float32))) * mask  # (B, L)
    grad_in = jnp.einsum("bl,bld->bd", g, v_path)
    c_in = _row_scale(syn0.shape[0], centers)
    grad_in = grad_in / c_in[centers][:, None]
    grad_path = g[..., None] * v_in[:, None, :]
    pts_flat = points.reshape(-1)
    c_path = jnp.sqrt(jnp.maximum(jnp.zeros(syn1.shape[0], jnp.float32).at[pts_flat].add(mask.reshape(-1)), 1.0))
    grad_path_flat = grad_path.reshape(-1, grad_path.shape[-1]) \
        / c_path[pts_flat][:, None]
    syn0 = syn0.at[centers].add(-lr * grad_in)
    syn1 = syn1.at[pts_flat].add(-lr * grad_path_flat)
    return syn0, syn1, loss


_skipgram_hs_step = functools.partial(jax.jit, donate_argnums=(0, 1))(_skipgram_hs_math)


def _cbow_ns_math(syn0, syn1, context_idx, context_mask, targets, negatives, lr):
    """CBOW + negative sampling. context_idx: (B, W) padded window,
    context_mask: (B, W), targets: (B,), negatives: (B, K)."""
    v_ctx = syn0[context_idx] * context_mask[..., None]       # (B, W, D)
    denom = jnp.maximum(context_mask.sum(axis=1, keepdims=True), 1.0)
    h = v_ctx.sum(axis=1) / denom                             # (B, D) mean
    v_pos = syn1[targets]
    v_neg = syn1[negatives]
    pos_score = jnp.einsum("bd,bd->b", h, v_pos)
    neg_score = jnp.einsum("bd,bkd->bk", h, v_neg)
    loss = -jnp.mean(jax.nn.log_sigmoid(pos_score)) \
           - jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg_score), axis=1))
    g_pos = _sigmoid(pos_score) - 1.0
    g_neg = _sigmoid(neg_score)
    grad_h = g_pos[:, None] * v_pos + jnp.einsum("bk,bkd->bd", g_neg, v_neg)
    grad_ctx = (grad_h / denom)[:, None, :] * context_mask[..., None]  # (B, W, D)
    neg_flat = negatives.reshape(-1)
    c_out = _row_scale(syn1.shape[0], targets, neg_flat)
    grad_tgt = (g_pos[:, None] * h) / c_out[targets][:, None]
    grad_neg_flat = (g_neg[..., None] * h[:, None, :]).reshape(-1, h.shape[-1]) \
        / c_out[neg_flat][:, None]
    ctx_flat = context_idx.reshape(-1)
    c_ctx = jnp.sqrt(jnp.maximum(jnp.zeros(syn0.shape[0], jnp.float32).at[ctx_flat].add(context_mask.reshape(-1)), 1.0))
    grad_ctx_flat = grad_ctx.reshape(-1, grad_ctx.shape[-1]) \
        / c_ctx[ctx_flat][:, None]
    syn1 = syn1.at[targets].add(-lr * grad_tgt)
    syn1 = syn1.at[neg_flat].add(-lr * grad_neg_flat)
    syn0 = syn0.at[ctx_flat].add(-lr * grad_ctx_flat)
    return syn0, syn1, loss


_cbow_ns_step = functools.partial(jax.jit, donate_argnums=(0, 1))(_cbow_ns_math)


@jax.jit
def _skipgram_ns_infer_step(vec, syn1, contexts, negatives, lr):  # jaxlint: disable=missing-donate
    """Inference-only skip-gram NS: update a single doc vector ``vec`` (1, D)
    against a FROZEN output table (ParagraphVectors.inferVector). No donation
    so the caller's tables stay valid."""
    v_in = jnp.broadcast_to(vec[0], (contexts.shape[0], vec.shape[1]))
    v_pos = syn1[contexts]
    v_neg = syn1[negatives]
    pos_score = jnp.einsum("bd,bd->b", v_in, v_pos)
    neg_score = jnp.einsum("bd,bkd->bk", v_in, v_neg)
    g_pos = _sigmoid(pos_score) - 1.0
    g_neg = _sigmoid(neg_score)
    grad = (g_pos[:, None] * v_pos + jnp.einsum("bk,bkd->bd", g_neg, v_neg)).sum(0)
    return vec - lr * grad[None, :]


@dataclass(frozen=True)
class SkipGram:
    """``learning/impl/elements/SkipGram.java`` marker config."""
    name: str = "SkipGram"


@dataclass(frozen=True)
class CBOW:
    """``learning/impl/elements/CBOW.java`` marker config."""
    name: str = "CBOW"


class SequenceVectors:
    """Generic embedding trainer over sequences of vocab indices.

    Builder-parity with ``SequenceVectors.java`` hyperparameters: layer_size,
    window, negative (K; 0 => hierarchical softmax), learning_rate ->
    min_learning_rate linear decay, subsampling of frequent tokens, epochs,
    batch_size, seed.
    """

    def __init__(self, vocab: VocabCache, layer_size: int = 100, window: int = 5,
                 negative: int = 5, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, sampling: float = 0.0,
                 epochs: int = 1, batch_size: int = 2048, seed: int = 42,
                 algorithm=None):
        self.vocab = vocab
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.sampling = sampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = algorithm or SkipGram()
        V = len(vocab)
        rng = np.random.default_rng(seed)
        # Reference init: syn0 uniform in [-0.5/D, 0.5/D], syn1 zeros.
        self.syn0 = jnp.asarray(
            (rng.random((V, layer_size), dtype=np.float32) - 0.5) / layer_size)
        self.syn1 = jnp.zeros((V, layer_size), jnp.float32)
        self._neg_probs = unigram_table(vocab)
        if negative == 0:
            self._codes, self._points, self._hs_mask = huffman_tensors(vocab)
        self._step_ns = _skipgram_ns_step
        self._step_hs = _skipgram_hs_step
        self._step_cbow = _cbow_ns_step

    # ----- host-side sampling of one epoch of training pairs ---------------

    def _sample_pairs(self, sequences: Sequence[np.ndarray], rng: np.random.Generator
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Dynamic-window skip-gram pair generation (SkipGram.java reduces the
        window uniformly per center, word2vec-style) + frequent-word
        subsampling (SequenceVectors 'sampling' knob)."""
        centers: List[np.ndarray] = []
        contexts: List[np.ndarray] = []
        keep_prob = None
        if self.sampling > 0:
            freq = self.vocab.counts() / max(self.vocab.total_count, 1)
            keep_prob = np.minimum(
                1.0, np.sqrt(self.sampling / np.maximum(freq, 1e-12))
                + self.sampling / np.maximum(freq, 1e-12))
        for seq in sequences:
            seq = np.asarray(seq, dtype=np.int64)
            if keep_prob is not None and len(seq):
                seq = seq[rng.random(len(seq)) < keep_prob[seq]]
            n = len(seq)
            if n < 2:
                continue
            b = rng.integers(1, self.window + 1, size=n)
            for i in range(n):
                lo, hi = max(0, i - int(b[i])), min(n, i + int(b[i]) + 1)
                ctx = np.concatenate([seq[lo:i], seq[i + 1:hi]])
                if len(ctx):
                    centers.append(np.full(len(ctx), seq[i]))
                    contexts.append(ctx)
        if not centers:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        return np.concatenate(centers), np.concatenate(contexts)

    def _window_arrays(self, sequences: Sequence[np.ndarray], rng: np.random.Generator):
        """CBOW batches: padded context windows per target."""
        W = 2 * self.window
        tgt, ctx, msk = [], [], []
        for seq in sequences:
            seq = np.asarray(seq, dtype=np.int64)
            n = len(seq)
            if n < 2:
                continue
            b = rng.integers(1, self.window + 1, size=n)
            for i in range(n):
                lo, hi = max(0, i - int(b[i])), min(n, i + int(b[i]) + 1)
                c = np.concatenate([seq[lo:i], seq[i + 1:hi]])[:W]
                if not len(c):
                    continue
                pad = np.zeros(W, np.int64)
                m = np.zeros(W, np.float32)
                pad[:len(c)] = c
                m[:len(c)] = 1.0
                tgt.append(seq[i]); ctx.append(pad); msk.append(m)
        if not tgt:
            return (np.zeros(0, np.int64), np.zeros((0, W), np.int64),
                    np.zeros((0, W), np.float32))
        return np.asarray(tgt), np.stack(ctx), np.stack(msk)

    # ----- training --------------------------------------------------------

    def fit(self, sequences: Iterable[Sequence[int]]) -> List[float]:
        """Train on index sequences; returns per-epoch mean losses."""
        seqs = [np.asarray(s, dtype=np.int64) for s in sequences]
        rng = np.random.default_rng(self.seed)
        losses: List[float] = []
        total_steps = None
        step = 0
        for epoch in range(self.epochs):
            ep_loss, nb = 0.0, 0
            if isinstance(self.algorithm, CBOW):
                tgt, ctx, msk = self._window_arrays(seqs, rng)
                order = rng.permutation(len(tgt))
                tgt, ctx, msk = tgt[order], ctx[order], msk[order]
                if total_steps is None:
                    total_steps = max(1, self.epochs * ((len(tgt) + self.batch_size - 1)
                                                        // max(self.batch_size, 1)))
                for s in range(0, len(tgt), self.batch_size):
                    bt, bc, bm = tgt[s:s + self.batch_size], ctx[s:s + self.batch_size], \
                        msk[s:s + self.batch_size]
                    bt, bc, bm = self._pad_batch3(bt, bc, bm)
                    neg = rng.choice(len(self.vocab), size=(len(bt), max(self.negative, 1)),
                                     p=self._neg_probs)
                    lr = self._lr(step, total_steps)
                    self.syn0, self.syn1, loss = self._step_cbow(
                        self.syn0, self.syn1, jnp.asarray(bc), jnp.asarray(bm),
                        jnp.asarray(bt), jnp.asarray(neg), lr)
                    ep_loss += float(loss); nb += 1; step += 1
            else:
                centers, contexts = self._sample_pairs(seqs, rng)
                order = rng.permutation(len(centers))
                centers, contexts = centers[order], contexts[order]
                if total_steps is None:
                    total_steps = max(1, self.epochs * ((len(centers) + self.batch_size - 1)
                                                        // max(self.batch_size, 1)))
                for s in range(0, len(centers), self.batch_size):
                    bc, bx = centers[s:s + self.batch_size], contexts[s:s + self.batch_size]
                    bc, bx = self._pad_batch(bc), self._pad_batch(bx)
                    lr = self._lr(step, total_steps)
                    if self.negative > 0:
                        neg = rng.choice(len(self.vocab), size=(len(bc), self.negative),
                                         p=self._neg_probs)
                        self.syn0, self.syn1, loss = self._step_ns(
                            self.syn0, self.syn1, jnp.asarray(bc), jnp.asarray(bx),
                            jnp.asarray(neg), lr)
                    else:
                        self.syn0, self.syn1, loss = self._step_hs(
                            self.syn0, self.syn1, jnp.asarray(bc),
                            jnp.asarray(self._codes[bx]), jnp.asarray(self._points[bx]),
                            jnp.asarray(self._hs_mask[bx]), lr)
                    ep_loss += float(loss); nb += 1; step += 1
            losses.append(ep_loss / max(nb, 1))
        return losses

    def _lr(self, step: int, total: int) -> float:
        frac = min(step / max(total, 1), 1.0)
        return max(self.learning_rate * (1.0 - frac), self.min_learning_rate)

    def _pad_batch(self, arr: np.ndarray) -> np.ndarray:
        """Pad the trailing partial batch to batch_size (repeating index 0 with
        zero-ish effect is avoided by clipping lr impact — instead repeat the
        batch's own rows) so XLA compiles exactly one batch shape."""
        if len(arr) == self.batch_size or len(arr) == 0:
            return arr
        reps = int(np.ceil(self.batch_size / len(arr)))
        return np.tile(arr, (reps,) + (1,) * (arr.ndim - 1))[:self.batch_size]

    def _pad_batch3(self, a, b, c):
        return self._pad_batch(a), self._pad_batch(b), self._pad_batch(c)

    # ----- lookup API (WordVectors.java surface) ---------------------------

    def vector(self, index: int) -> np.ndarray:
        return np.asarray(self.syn0[index])

    @property
    def vectors(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def similarity(self, i: int, j: int) -> float:
        a, b = self.vector(i), self.vector(j)
        den = np.linalg.norm(a) * np.linalg.norm(b)
        return float(a @ b / den) if den > 0 else 0.0

    def nearest(self, index: int, top_n: int = 10) -> List[Tuple[int, float]]:
        M = self.vectors
        norms = np.linalg.norm(M, axis=1) + 1e-12
        sims = (M @ M[index]) / (norms * norms[index])
        sims[index] = -np.inf
        top = np.argsort(-sims)[:top_n]
        return [(int(t), float(sims[t])) for t in top]


class ShardedSequenceVectors(SequenceVectors):
    """Distributed embedding training over a device mesh — the TPU-native
    redesign of the reference's Spark embedding layer
    (``dl4j-spark-nlp-java8/.../sequencevectors/SparkSequenceVectors.java:174``
    trains with a VoidParameterServer holding sharded lookup tables;
    ``models/embeddings/inmemory/InMemoryLookupTable.java`` is the
    single-machine table it shards).

    Design: syn0/syn1 rows (the vocab dim) are sharded over the ``model``
    mesh axis — the parameter-server shard map, expressed as a NamedSharding;
    batches are sharded over the ``data`` axis. The SAME update math as the
    single-device steps runs under jit with those shardings, and GSPMD
    inserts the gather/scatter collectives the reference routed through
    Aeron. SPMD partitioning preserves semantics, so sharded training is
    numerically identical to single-device training — asserted by
    ``tests/test_nlp.py``'s equivalence test.

    The vocab is padded up to a multiple of the model-axis size (padded rows
    are never sampled: indices always come from the real vocab).
    """

    def __init__(self, vocab: VocabCache, mesh=None, **kw):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh

        super().__init__(vocab, **kw)
        if mesh is None:
            n = len(jax.devices())
            mesh = make_mesh({DATA_AXIS: 1, MODEL_AXIS: n})
        self.mesh = mesh
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        mp = axes.get(MODEL_AXIS, 1)
        dp = axes.get(DATA_AXIS, 1)
        if self.batch_size % max(dp, 1):
            raise ValueError(f"batch_size {self.batch_size} must divide over "
                             f"data axis {dp}")
        V, D = self.syn0.shape
        pad_rows = (-V) % mp
        if pad_rows:
            z = jnp.zeros((pad_rows, D), self.syn0.dtype)
            self.syn0 = jnp.concatenate([self.syn0, z])
            self.syn1 = jnp.concatenate([self.syn1, z])
        self._V_logical = V
        table_sh = NamedSharding(mesh, P(MODEL_AXIS, None))
        batch_sh = NamedSharding(mesh, P(DATA_AXIS))
        self.syn0 = jax.device_put(self.syn0, table_sh)
        self.syn1 = jax.device_put(self.syn1, table_sh)

        def sharded(fn, n_batch_args):
            # tables sharded over vocab rows, index batches over data, lr
            # replicated; outputs keep the table sharding
            in_sh = (table_sh, table_sh) + (batch_sh,) * n_batch_args + (None,)
            return jax.jit(fn, in_shardings=in_sh,
                           out_shardings=(table_sh, table_sh, None),
                           donate_argnums=(0, 1))

        self._step_ns = sharded(_skipgram_ns_math, 3)
        self._step_hs = sharded(_skipgram_hs_math, 4)
        self._step_cbow = sharded(_cbow_ns_math, 4)

    @property
    def vectors(self) -> np.ndarray:
        return np.asarray(self.syn0)[: self._V_logical]
