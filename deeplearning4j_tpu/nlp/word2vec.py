"""Word2Vec — user-facing embedding model atop SequenceVectors, parity with
``models/word2vec/Word2Vec.java`` (builder surface: minWordFrequency,
layerSize, windowSize, negativeSample, learningRate/minLearningRate, sampling,
epochs/iterations, seed, elementsLearningAlgorithm).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .sequencevectors import CBOW, SequenceVectors, SkipGram
from .tokenization import (CommonPreprocessor, DefaultTokenizerFactory,
                           SentenceIterator, TokenizerFactory)
from .vocab import VocabCache, VocabConstructor


class Word2Vec:
    """Builder-style Word2Vec (Word2Vec.java:633 LoC).

    >>> w2v = Word2Vec(min_word_frequency=1, layer_size=32, window_size=5)
    >>> w2v.fit(["the quick brown fox", ...])
    >>> w2v.words_nearest("fox", 5)
    """

    def __init__(self, min_word_frequency: int = 5, layer_size: int = 100,
                 window_size: int = 5, negative_sample: int = 5,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-4,
                 sampling: float = 0.0, epochs: int = 1, batch_size: int = 2048,
                 seed: int = 42, use_cbow: bool = False,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.negative_sample = negative_sample
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.sampling = sampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.use_cbow = use_cbow
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.sv: Optional[SequenceVectors] = None

    # -- training ----------------------------------------------------------

    def _tokenize(self, sentences: Iterable[str]) -> List[List[str]]:
        return [self.tokenizer.create(s).get_tokens() for s in sentences]

    def fit(self, sentences: Iterable[str]) -> List[float]:
        sents = list(sentences) if not isinstance(sentences, SentenceIterator) else list(sentences)
        token_lists = self._tokenize(sents)
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=self.negative_sample == 0).build(token_lists)
        self.sv = SequenceVectors(
            self.vocab, layer_size=self.layer_size, window=self.window_size,
            negative=self.negative_sample, learning_rate=self.learning_rate,
            min_learning_rate=self.min_learning_rate, sampling=self.sampling,
            epochs=self.epochs, batch_size=self.batch_size, seed=self.seed,
            algorithm=CBOW() if self.use_cbow else SkipGram())
        seqs = [[self.vocab.index_of(t) for t in toks if t in self.vocab]
                for toks in token_lists]
        return self.sv.fit([s for s in seqs if len(s) > 1])

    # -- WordVectors query surface (models/embeddings/wordvectors) ---------

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        if not self.has_word(word):
            return None
        return self.sv.vector(self.vocab.index_of(word))

    def similarity(self, a: str, b: str) -> float:
        if not (self.has_word(a) and self.has_word(b)):
            return float("nan")
        return self.sv.similarity(self.vocab.index_of(a), self.vocab.index_of(b))

    def words_nearest(self, word: str, top_n: int = 10) -> List[Tuple[str, float]]:
        if not self.has_word(word):
            return []
        pairs = self.sv.nearest(self.vocab.index_of(word), top_n)
        return [(self.vocab.word_for(i), s) for i, s in pairs]

    @property
    def vectors(self) -> np.ndarray:
        return self.sv.vectors

    def vocab_words(self) -> List[str]:
        return [w.word for w in self.vocab.words]
