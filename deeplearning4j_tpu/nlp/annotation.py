"""Text annotation pipeline — the deeplearning4j-nlp-uima equivalent.

The reference's UIMA pack (deeplearning4j-nlp-uima/, ~3.2k LoC) wraps UIMA
analysis engines for sentence segmentation, tokenization, POS tagging and
stemming, and exposes them through the same TokenizerFactory /
SentenceIterator SPIs the rest of the NLP stack consumes
(UimaTokenizerFactory.java, PosUimaTokenizerFactory.java,
UimaSentenceIterator.java, annotator/{SentenceAnnotator,TokenizerAnnotator,
PoStagger,StemmerAnnotator}.java). The Java-ecosystem machinery (UIMA CAS,
OpenNLP models, ClearTK type systems) is replaced here by a light
annotator-pipeline of the same shape:

- :class:`Annotation` — a typed text span with features (the CAS record),
- :class:`AnnotatorPipeline` — an ordered annotator chain over a document
  (the AnalysisEngine aggregate),
- :class:`SentenceAnnotator` — rule-based boundary detection (latin
  terminators with abbreviation/initial/number guards + CJK 。！？),
- :class:`TokenizerAnnotator` — token spans inside each sentence via any
  :class:`~.tokenization.TokenizerFactory` (so the CJK packs plug in),
- :class:`PosAnnotator` — POS features per token: a compact suffix/lexicon
  English tagger + a Japanese table derived from the ipadic-segmented
  corpus (``data/ja_pos.txt``, built by scripts/grow_ja_lexicon.py),
- :class:`StemmerAnnotator` — Porter stemmer (SnowballStemmer parity).

API-parity adapters: :class:`AnnotationTokenizerFactory`
(=UimaTokenizerFactory: sentence-aware tokenization through the pipeline),
:class:`PosFilterTokenizerFactory` (=PosUimaTokenizerFactory: keep only
tokens whose POS is in ``allowed`` — the reference uses this for
noun-phrase extraction), :class:`AnnotationSentenceIterator`
(=UimaSentenceIterator: stream sentences from documents).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, Iterable, List, Optional, Sequence

from .tokenization import SentenceIterator, Tokenizer, TokenizerFactory

# ---------------------------------------------------------------- records


@dataclass
class Annotation:
    """A typed span over the document text (the UIMA CAS annotation)."""

    begin: int
    end: int
    type: str                      # "sentence" | "token" | ...
    features: Dict[str, str] = field(default_factory=dict)

    def covered_text(self, text: str) -> str:
        return text[self.begin:self.end]


class Document:
    """Annotated document: raw text + annotations by type (the CAS)."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: List[Annotation] = []

    def select(self, type_: str) -> List[Annotation]:
        return [a for a in self.annotations if a.type == type_]

    def covered(self, a: Annotation) -> str:
        return a.covered_text(self.text)


class AnnotatorPipeline:
    """Ordered annotator chain (AnalysisEngineFactory.createEngine
    aggregate parity): ``process`` runs each annotator over the document
    in order; later annotators see earlier ones' annotations."""

    def __init__(self, annotators: Sequence["Annotator"]):
        self.annotators = list(annotators)

    def process(self, text: str) -> Document:
        doc = Document(text)
        for a in self.annotators:
            a.annotate(doc)
        return doc

    @staticmethod
    def default(tokenizer_factory: Optional[TokenizerFactory] = None,
                pos: bool = False) -> "AnnotatorPipeline":
        """The reference's default engine: sentence + tokenizer
        (+ optional POS), UimaTokenizerFactory.defaultAnalysisEngine()."""
        chain: List[Annotator] = [SentenceAnnotator(),
                                  TokenizerAnnotator(tokenizer_factory)]
        if pos:
            chain.append(PosAnnotator())
        return AnnotatorPipeline(chain)


class Annotator:
    def annotate(self, doc: Document) -> None:
        raise NotImplementedError


# ------------------------------------------------------------- sentences

#: words that are ALWAYS abbreviations before a '.' (titles, latinisms)
_ABBREV = frozenset("""
mr mrs ms dr prof sr jr vs etc e.g i.e cf inc ltd corp approx st al
u.s u.k a.m p.m ph.d m.d b.a m.a d.c
""".split())
# 'st'/'al' stay unconditional: their dominant uses continue with an
# UPPERCASE name ("St. Louis") or a bracket ("et al. (2020)"), which the
# right-context rule below would wrongly treat as a sentence start
#: words that are abbreviations ONLY with right context (a following
#: digit or lowercase continuation): months/weekdays before dates, and
#: words that double as ordinary English ("no", "fig", "st", "est")
_ABBREV_CTX = frozenset("""
co dept est fig no vol pp jan feb mar apr jun jul aug sep sept oct
nov dec mon tue wed thu fri sat sun
""".split())

_TERMINATORS = ".!?。！？…"
_CLOSERS = "\"')]}»」』）"


class SentenceAnnotator(Annotator):
    """Rule-based sentence boundary detection (annotator/SentenceAnnotator
    parity — the reference delegates to ClearTK's sentence engine; this is
    a self-contained rule engine honest about its scope):

    - latin '.', '!', '?' terminate unless the preceding word is a known
      abbreviation, a single initial (J.), or the dot sits between digits
      (3.14),
    - CJK 。！？ and ellipsis always terminate,
    - trailing quotes/brackets attach to the finished sentence,
    - newlines (paragraph breaks) always terminate."""

    def annotate(self, doc: Document) -> None:
        text = doc.text
        n = len(text)
        start = 0
        i = 0
        while i < n:
            ch = text[i]
            if ch == "\n":
                self._emit(doc, start, i)
                start = i + 1
                i += 1
                continue
            if ch in _TERMINATORS:
                if ch == "." and self._is_non_boundary_dot(text, i):
                    i += 1
                    continue
                j = i + 1
                while j < n and text[j] in _TERMINATORS:  # "?!", "..."
                    j += 1
                while j < n and text[j] in _CLOSERS:
                    j += 1
                self._emit(doc, start, j)
                start = j
                i = j
                continue
            i += 1
        self._emit(doc, start, n)

    @staticmethod
    def _is_non_boundary_dot(text: str, i: int) -> bool:
        # digit.digit (3.14) — not a boundary
        if 0 < i < len(text) - 1 and text[i - 1].isdigit() and text[i + 1].isdigit():
            return True
        # preceding word is an abbreviation or a single initial
        j = i - 1
        while j >= 0 and (text[j].isalpha() or text[j] == "."):
            j -= 1
        word = text[j + 1:i].lower()
        if not word:
            return False
        if word in _ABBREV or (len(word) == 1 and word.isalpha()):
            return True
        if word in _ABBREV_CTX:
            # "Jan. 5" / "fig. 3" / "no. 12" continue; "The answer was
            # no. He left." terminates (next sentence starts uppercase)
            k = i + 1
            while k < len(text) and text[k].isspace():
                k += 1
            return k < len(text) and (text[k].isdigit() or text[k].islower())
        return False

    @staticmethod
    def _emit(doc: Document, begin: int, end: int) -> None:
        while begin < end and doc.text[begin].isspace():
            begin += 1
        while end > begin and doc.text[end - 1].isspace():
            end -= 1
        if end > begin:
            doc.annotations.append(Annotation(begin, end, "sentence"))


# ---------------------------------------------------------------- tokens


class ScriptAwareTokenizerFactory(TokenizerFactory):
    """The pipeline's default tokenizer: latin text splits on whitespace
    with punctuation stripped; CJK runs route through the language packs
    (hangul → Korean, kana present → Japanese, han-only → Chinese) — so
    one annotator chain handles mixed-language documents, the role the
    UIMA engine aggregate plays in the reference."""

    def create(self, text: str) -> Tokenizer:
        from .cjk import _char_block

        toks: List[str] = []

        def emit(seg: str, kind: str) -> None:
            if kind == "cjk":
                toks.extend(self._cjk_factory(seg).create(seg).get_tokens())
            else:
                stripped = (w.strip("'\".,;:!?()[]{}«»「」『』")
                            for w in seg.split())
                toks.extend(t for t in stripped if t)

        run: List[str] = []
        run_kind: Optional[str] = None
        for ch in text:
            b = _char_block(ch)
            kind = ("cjk" if b in ("han", "hiragana", "katakana", "hangul")
                    or ch in "ー々。、！？" else "latin")
            if run_kind is not None and kind != run_kind:
                emit("".join(run), run_kind)
                run.clear()
            run.append(ch)
            run_kind = kind
        if run:
            emit("".join(run), run_kind)
        return Tokenizer(toks, self._pre)

    @staticmethod
    @lru_cache(maxsize=None)
    def _factories():
        from .cjk import (ChineseTokenizerFactory, JapaneseTokenizerFactory,
                          KoreanTokenizerFactory)

        return (ChineseTokenizerFactory(), JapaneseTokenizerFactory(),
                KoreanTokenizerFactory())

    def _cjk_factory(self, seg: str):
        from .cjk import _char_block

        zh, ja, ko = self._factories()
        blocks = {_char_block(c) for c in seg}
        if "hangul" in blocks:
            return ko
        if "hiragana" in blocks or "katakana" in blocks:
            return ja
        return zh


class TokenizerAnnotator(Annotator):
    """Token spans inside each sentence (annotator/TokenizerAnnotator
    parity). Tokens come from any TokenizerFactory — the span positions
    are recovered by left-to-right alignment of the factory's tokens
    against the sentence text (factories may drop punctuation)."""

    def __init__(self, factory: Optional[TokenizerFactory] = None):
        self.factory = factory or ScriptAwareTokenizerFactory()

    def annotate(self, doc: Document) -> None:
        sentences = doc.select("sentence") or [
            Annotation(0, len(doc.text), "sentence")]
        for s in sentences:
            sent_text = doc.covered(s)
            pos = 0
            for tok in self.factory.create(sent_text).get_tokens():
                at = sent_text.find(tok, pos)
                if at < 0:  # preprocessed token (lowercased etc.): align
                    at = sent_text.lower().find(tok.lower(), pos)
                    if at < 0:
                        continue
                doc.annotations.append(
                    Annotation(s.begin + at, s.begin + at + len(tok),
                               "token"))
                pos = at + len(tok)


# ------------------------------------------------------------------ POS

# Compact English tagger: closed-class lexicon + suffix rules. The
# reference ships OpenNLP's statistical tagger; the honest scope here is
# the POS-FILTERing use case (PosUimaTokenizerFactory keeps nouns/verbs),
# which needs coarse tags, not treebank precision.
_EN_CLOSED = {
    **{w: "DT" for w in ("the", "a", "an", "this", "that", "these", "those")},
    **{w: "IN" for w in ("in", "on", "at", "by", "for", "with", "of", "to",
                         "from", "into", "over", "under", "about")},
    **{w: "CC" for w in ("and", "or", "but", "nor", "so", "yet")},
    **{w: "PRP" for w in ("i", "you", "he", "she", "it", "we", "they",
                          "me", "him", "her", "us", "them")},
    **{w: "MD" for w in ("can", "could", "will", "would", "shall",
                         "should", "may", "might", "must")},
    **{w: "VB" for w in ("is", "are", "was", "were", "be", "been", "am",
                         "has", "have", "had", "do", "does", "did")},
}


def _en_pos(word: str) -> str:
    w = word.lower()
    if w in _EN_CLOSED:
        return _EN_CLOSED[w]
    if w[0].isdigit():
        return "CD"
    if w.endswith("ly"):
        return "RB"
    if w.endswith(("ing", "ed")):
        return "VB"
    if w.endswith(("ous", "ful", "ive", "able", "ible", "al", "ic")):
        return "JJ"
    if word[0].isupper():
        return "NNP"
    return "NN"


@lru_cache(maxsize=None)
def _ja_pos_table() -> dict:
    from pathlib import Path

    p = Path(__file__).parent / "data" / "ja_pos.txt"
    out = {}
    if p.exists():
        for line in p.read_text(encoding="utf-8").splitlines():
            if line and not line.startswith("#"):
                parts = line.split()
                if len(parts) == 2:
                    out[parts[0]] = parts[1]
    return out


class PosAnnotator(Annotator):
    """POS feature per token (annotator/PoStagger parity). Honest scope:

    - English (latin-script) tokens: the suffix/lexicon tagger,
    - Japanese surfaces: the ipadic-corpus table (名詞/動詞/助詞...),
      with unseen all-han compounds defaulting to 名詞 (kanji compounds
      outside the table are overwhelmingly nouns),
    - Korean: particles from the morpheme inventory tag 조사, everything
      else 'X' (no offline ko tagger exists in this environment),
    - anything untaggable (incl. CJK punctuation): 'X' — so a
      :class:`PosFilterTokenizerFactory` never passes tokens the tagger
      has no evidence about."""

    def annotate(self, doc: Document) -> None:
        from .cjk import KoreanMorphemeTokenizerFactory, _char_block

        ja = _ja_pos_table()
        ko_particles = frozenset(KoreanMorphemeTokenizerFactory.PARTICLES)
        for t in doc.select("token"):
            w = doc.covered(t)
            blocks = {_char_block(c) for c in w}
            if (blocks <= {"latin", "punct"} and "latin" in blocks):
                # internal punctuation (John's, co-worker, 3.14) must not
                # make an ordinary English token untaggable
                t.features["pos"] = _en_pos(w)
            elif w in ja:
                t.features["pos"] = ja[w]
            elif "hangul" in blocks:
                t.features["pos"] = "조사" if w in ko_particles else "X"
            elif blocks <= {"han"} and len(w) >= 2:
                t.features["pos"] = "名詞"  # unseen kanji compound
            elif blocks <= {"katakana"} and len(w) >= 2:
                t.features["pos"] = "名詞"  # katakana loanword (モデル,
                #                            データ — overwhelmingly nouns;
                #                            the corpus predates them)
            else:
                t.features["pos"] = "X"


# -------------------------------------------------------------- stemming


def porter_stem(word: str) -> str:
    """Porter stemming algorithm (StemmerAnnotator / SnowballStemmer
    parity) — the standard 1980 rule cascade, steps 1a-5b."""
    w = word.lower()
    if len(w) <= 2:
        return w

    def cons(s, i):
        c = s[i]
        if c in "aeiou":
            return False
        if c == "y":
            return i == 0 or not cons(s, i - 1)
        return True

    def measure(s):
        m, prev_v = 0, False
        for i in range(len(s)):
            v = not cons(s, i)
            if prev_v and not v:
                m += 1
            prev_v = v
        return m

    def has_vowel(s):
        return any(not cons(s, i) for i in range(len(s)))

    def double_cons(s):
        return len(s) >= 2 and s[-1] == s[-2] and cons(s, len(s) - 1)

    def cvc(s):
        return (len(s) >= 3 and cons(s, len(s) - 3)
                and not cons(s, len(s) - 2) and cons(s, len(s) - 1)
                and s[-1] not in "wxy")

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("s") and not w.endswith("ss"):
        w = w[:-1]
    # step 1b
    if w.endswith("eed"):
        if measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and has_vowel(w[:-2]):
        w = w[:-2]
        w = _post1b(w, double_cons, cvc, measure)
    elif w.endswith("ing") and has_vowel(w[:-3]):
        w = w[:-3]
        w = _post1b(w, double_cons, cvc, measure)
    # step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2/3/4 suffix maps (m-conditioned)
    for cond_m, pairs in ((0, _STEP2), (0, _STEP3), (1, _STEP4)):
        for suf, rep in pairs:
            if w.endswith(suf):
                stem = w[:-len(suf)]
                if measure(stem) > cond_m:
                    w = stem + rep
                break
    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = measure(stem)
        if m > 1 or (m == 1 and not cvc(stem)):
            w = stem
    # step 5b
    if measure(w) > 1 and double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


def _post1b(w, double_cons, cvc, measure):
    if w.endswith(("at", "bl", "iz")):
        return w + "e"
    if double_cons(w) and w[-1] not in "lsz":
        return w[:-1]
    if measure(w) == 1 and cvc(w):
        return w + "e"
    return w


_STEP2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
          ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
          ("alli", "al"), ("entli", "ent"), ("eli", "e"), ("ousli", "ous"),
          ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
          ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
          ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"),
          ("biliti", "ble")]
_STEP3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
          ("ical", "ic"), ("ful", ""), ("ness", "")]
_STEP4 = [("ement", ""), ("ance", ""), ("ence", ""), ("able", ""),
          ("ible", ""), ("ant", ""), ("ment", ""), ("ent", ""),
          ("sion", "s"), ("tion", "t"), ("ou", ""), ("ism", ""),
          ("ate", ""), ("iti", ""), ("ous", ""), ("ive", ""), ("ize", ""),
          ("er", ""), ("ic", ""), ("al", "")]


class StemmerAnnotator(Annotator):
    """Adds a ``stem`` feature to every token (StemmerAnnotator parity)."""

    def annotate(self, doc: Document) -> None:
        for t in doc.select("token"):
            w = doc.covered(t)
            if w.isascii() and w.isalpha():
                t.features["stem"] = porter_stem(w)


# -------------------------------------------------- SPI parity adapters


class AnnotationTokenizerFactory(TokenizerFactory):
    """UimaTokenizerFactory parity: tokenization through the full
    sentence+token pipeline, so tokens never straddle sentence
    boundaries and the same engine drives iterators and factories."""

    def __init__(self, pipeline: Optional[AnnotatorPipeline] = None):
        super().__init__()
        self.pipeline = pipeline or AnnotatorPipeline.default()

    def create(self, text: str) -> Tokenizer:
        doc = self.pipeline.process(text)
        toks = [doc.covered(t) for t in doc.select("token")]
        return Tokenizer(toks, self._pre)


class PosFilterTokenizerFactory(TokenizerFactory):
    """PosUimaTokenizerFactory parity: emit only tokens whose coarse POS
    is in ``allowed`` (the reference's noun-phrase extraction path).
    English tags are Penn-style prefixes (NN/NNP/VB/JJ/RB/...), Japanese
    ipadic top-level classes (名詞/動詞/形容詞/...); matching is by
    prefix, so allowed={"NN"} keeps NN and NNP."""

    def __init__(self, allowed: Iterable[str],
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        super().__init__()
        self.allowed = tuple(allowed)
        self.pipeline = AnnotatorPipeline([
            SentenceAnnotator(), TokenizerAnnotator(tokenizer_factory),
            PosAnnotator()])

    def create(self, text: str) -> Tokenizer:
        doc = self.pipeline.process(text)
        toks = [doc.covered(t) for t in doc.select("token")
                if t.features.get("pos", "").startswith(self.allowed)]
        return Tokenizer(toks, self._pre)


class AnnotationSentenceIterator(SentenceIterator):
    """UimaSentenceIterator parity: stream sentences from documents
    through the SentenceAnnotator."""

    def __init__(self, documents: Iterable[str],
                 pipeline: Optional[AnnotatorPipeline] = None):
        # keep only the document handles; sentences stream lazily per
        # document in __iter__ (BasicLineIterator's pattern) — a large
        # corpus never materializes all sentences at once
        self.documents = list(documents)
        self.pipeline = pipeline or AnnotatorPipeline([SentenceAnnotator()])

    def __iter__(self):
        for d in self.documents:
            doc = self.pipeline.process(d)
            for s in doc.select("sentence"):
                yield doc.covered(s)

    def reset(self) -> None:
        pass
