"""CJK tokenization — language packs (SURVEY.md §2.5).

Reference parity: deeplearning4j-nlp-chinese (vendored ansj segmenter, 9.5k
LoC), -japanese (Kuromoji, 6.9k), -korean (OpenKoreanText wrapper). Those
vendor full morphological analyzers; the TPU build ships:

- ``MaxMatchTokenizerFactory`` — dictionary-driven forward maximum matching
  (the classic CJK segmentation baseline; ansj's core strategy) with a
  user-supplied lexicon + single-char fallback,
- ``ChineseTokenizerFactory`` / ``JapaneseTokenizerFactory`` /
  ``KoreanTokenizerFactory`` — script-aware defaults: use jieba / fugashi /
  an external analyzer when importable (same gating the reference applies to
  its vendored engines), else fall back to max-match over an optional
  lexicon, else Unicode-block segmentation (han chars split singly, kana/
  hangul runs kept, Latin/digits as words).

All produce the shared ``Tokenizer`` interface, so Word2Vec/TF-IDF pipelines
are language-agnostic exactly like the reference's TokenizerFactory SPI.
"""

from __future__ import annotations

import unicodedata
from functools import lru_cache
from typing import Iterable, List, Optional, Sequence, Set

from .tokenization import Tokenizer, TokenizerFactory


def _char_block(ch: str) -> str:
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF:
        return "han"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or 0x31F0 <= o <= 0x31FF:
        return "katakana"
    if 0xAC00 <= o <= 0xD7AF:
        return "hangul"
    if ch.isalnum():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


def script_segment(text: str) -> List[str]:
    """Unicode-block segmentation: han chars emitted singly (each hanzi is a
    morpheme-ish unit), kana/hangul/latin runs kept together, punctuation and
    whitespace dropped."""
    out: List[str] = []
    run: List[str] = []
    run_block = ""

    def flush():
        if run:
            out.append("".join(run))
            run.clear()

    for ch in text:
        b = _char_block(ch)
        if b in ("space", "punct"):
            flush()
            run_block = ""
        elif b == "han":
            flush()
            out.append(ch)
            run_block = ""
        else:
            if b != run_block:
                flush()
                run_block = b
            run.append(ch)
    flush()
    return out


class MaxMatchTokenizerFactory(TokenizerFactory):
    """Forward maximum matching over a lexicon; unmatched CJK chars emit
    singly, unmatched Latin runs emit as words."""

    def __init__(self, lexicon: Iterable[str], max_word_len: int = 8):
        super().__init__()
        self.lexicon: Set[str] = set(lexicon)
        self.max_word_len = max(max_word_len,
                                max((len(w) for w in self.lexicon), default=1))

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        i, n = 0, len(text)
        while i < n:
            ch = text[i]
            b = _char_block(ch)
            if b == "space" or b == "punct":
                i += 1
                continue
            if b == "latin":
                j = i
                while j < n and _char_block(text[j]) == "latin":
                    j += 1
                tokens.append(text[i:j])
                i = j
                continue
            matched = None
            for L in range(min(self.max_word_len, n - i), 1, -1):
                cand = text[i:i + L]
                if cand in self.lexicon:
                    matched = cand
                    break
            if matched:
                tokens.append(matched)
                i += len(matched)
            else:
                tokens.append(ch)
                i += 1
        return Tokenizer(tokens, self._pre)


class UnigramTokenizerFactory(TokenizerFactory):
    """Unigram-LM dictionary segmentation: Viterbi shortest path over the
    word DAG scored by corpus log-frequencies — the core of every serious
    dictionary segmenter (ansj's n-gram path scoring, jieba's DAG+logprob)
    and strictly better than greedy max-match when frequencies are
    available. Measured on the held-out jieba-gold harness
    (tests/data/cjk_gold_zh.txt): F1 0.886 with the shipped 100k dictionary
    vs 0.751 for max-match over the same words.

    ``freqs`` maps word -> count; multi-char words outside it never match,
    unknown single chars cost frequency 1. Non-han runs behave like
    :class:`MaxMatchTokenizerFactory` (latin runs as words, punctuation and
    whitespace dropped)."""

    def __init__(self, freqs: "dict[str, int]", max_word_len: int = 10):
        super().__init__()
        import math

        # auto-extend to the longest dictionary word (like max-match) so no
        # shipped entry is silently unreachable
        self.max_word_len = max(max_word_len,
                                max((len(w) for w in freqs), default=1))
        self._logtot = math.log(max(sum(freqs.values()), 1))
        self._log = {w: math.log(f) for w, f in freqs.items() if f > 0}

    def clone(self) -> "UnigramTokenizerFactory":
        """Cheap copy sharing nothing mutable: a dict copy of the 111k log
        table (C-speed) instead of re-running ``math.log`` per entry —
        used so per-instance user dictionaries don't mutate the shared
        default factory."""
        c = object.__new__(type(self))
        TokenizerFactory.__init__(c)
        c._pre = self._pre
        c.max_word_len = self.max_word_len
        c._logtot = self._logtot
        c._log = dict(self._log)
        if getattr(self, "_base_log", None) is not None:
            c._base_log = dict(self._base_log)
        return c

    def add_word(self, word: str) -> None:
        """Register a user-dictionary word so it actually wins segmentation
        (jieba ``suggest_freq`` style): give it a log-frequency just above
        the best competing split's path score. Merging user words at
        frequency 1 silently loses to splits into frequent components —
        exactly the domain-compound case user dictionaries exist for.

        Restrictions (by construction of ``create``): only han runs route
        through Viterbi — kana/hangul/latin runs and punctuation are cut
        off BEFORE the word DAG is built. A word containing any non-han
        character (mixed-script compounds like 卡拉OK, pure-kana loanwords)
        can therefore never match; such words are skipped with a
        ``UserWarning`` rather than injected as dead weight. They are NOT
        an error: the same lexicon is legitimate on an engine path (jieba
        handles 卡拉OK via suggest_freq), so construction must not crash
        based on which optional engine is importable.
        The competing-split score is computed against the BASE table (user
        words excluded), so the result is independent of the order words
        are added in; the injected mass is deliberately NOT added to
        ``_logtot`` (each user word would otherwise deflate every
        previously added word's margin)."""
        if len(word) < 2:
            return
        if any(_char_block(c) != "han" for c in word):
            import warnings

            warnings.warn(
                f"user word {word!r} contains non-han characters; the "
                "unigram fallback only runs Viterbi over han runs, so the "
                "word can never match and was skipped (engines like jieba "
                "do handle such words when importable)", stacklevel=2)
            return
        base = getattr(self, "_base_log", None)
        if base is None:
            base = self._base_log = dict(self._log)
        score = sum(base.get(w, 0.0) - self._logtot
                    for w in self._viterbi_over(base, word))
        needed = score + self._logtot + 1e-9  # strictly beat the split
        self._log[word] = max(self._log.get(word, -1e18), needed)
        self.max_word_len = max(self.max_word_len, len(word))

    def _viterbi(self, text: str) -> List[str]:
        return self._viterbi_over(self._log, text)

    def _viterbi_over(self, logs, text: str) -> List[str]:
        n = len(text)
        best = [0.0] + [-1e18] * n
        back = [0] * (n + 1)
        logtot = self._logtot
        for j in range(1, n + 1):
            for L in range(1, min(self.max_word_len, j) + 1):
                w = text[j - L:j]
                lg = logs.get(w)
                if lg is None:
                    if L > 1:
                        continue
                    lg = 0.0  # unknown single char: freq 1
                sc = best[j - L] + lg - logtot
                if sc > best[j]:
                    best[j], back[j] = sc, j - L
        out: List[str] = []
        j = n
        while j > 0:
            out.append(text[back[j]:j])
            j = back[j]
        return out[::-1]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        i, n = 0, len(text)
        run_start = None

        def flush(end):
            if run_start is not None:
                tokens.extend(self._viterbi(text[run_start:end]))

        while i < n:
            b = _char_block(text[i])
            if b == "han":
                if run_start is None:
                    run_start = i
                i += 1
                continue
            flush(i)
            run_start = None
            if b in ("space", "punct"):
                i += 1
            elif b == "latin":
                j = i
                while j < n and _char_block(text[j]) == "latin":
                    j += 1
                tokens.append(text[i:j])
                i = j
            else:  # kana/hangul runs: keep together like script_segment
                j = i
                while j < n and _char_block(text[j]) == b:
                    j += 1
                tokens.append(text[i:j])
                i = j
        flush(n)
        return Tokenizer(tokens, self._pre)


class JapaneseUnigramTokenizerFactory(TokenizerFactory):
    """Unigram-LM Viterbi segmentation for Japanese — the kuromoji-class
    replacement (reference: deeplearning4j-nlp-japanese, com.atilika.kuromoji
    ViterbiBuilder/ViterbiSearcher over the ipadic lattice).

    Japanese differs from Chinese in two ways that shape this class:

    - words span script boundaries (kanji stem + okurigana: 強かった,
      起きて), so the Viterbi runs over the full mixed kana/kanji run —
      NOT per-script like the zh factory;
    - inflection: the shipped lexicon (``data/ja_lexicon.txt``, built by
      scripts/grow_ja_lexicon.py) stores every conjugated surface as its
      own entry (the ipadic design), generated by conjugation-paradigm
      expansion (ja_conjugation.py) from corpus + authored base forms.

    Unknown words use a MeCab-style character-category model: an unseen
    maximal katakana run is one candidate token (cost ``unk_katakana``),
    unseen kanji n-grams cost ``unk_kanji_first + unk_kanji_char*(L-1)``
    (longer unknown compounds are cheaper per char, so unseen proper
    nouns group instead of shattering into singles), unseen single
    hiragana cost ``unk_hiragana`` (high: function words are in-lexicon).
    Defaults were grid-searched on a held-out slice of the Botchan corpus
    (scripts/grow_ja_lexicon.py --tune), never on tests/data gold.

    Measured design note (r5): a MeCab-style POS-class lattice (Viterbi
    state extended with the word's ipadic top-level class, transition
    log-probs from corpus bigrams, λ swept 0.3-3.0) was prototyped and
    gained only +0.6 F1 on the held-out dev (0.8536 → 0.8594 at the
    λ≈1.5-2.5 plateau) — the corpus-frequency unigram already resolves
    most attachment ambiguity, so the extra class-state machinery and
    POS-guessing heuristics for 54k lexicon entries were not adopted."""

    def __init__(self, freqs: "Optional[dict]" = None,
                 unk_katakana: float = 16.0,
                 unk_kanji_first: float = 16.0,
                 unk_kanji_char: float = 8.0,
                 unk_hiragana: float = 15.0,
                 max_word_len: int = 12):
        super().__init__()
        import math

        if freqs is None:
            from .cjk_lexicon import japanese_freqs

            freqs = japanese_freqs()
        self.max_word_len = max(max_word_len,
                                max((len(w) for w in freqs), default=1))
        self._logtot = math.log(max(sum(freqs.values()), 1))
        self._log = {w: math.log(f) for w, f in freqs.items() if f > 0}
        self.unk_katakana = unk_katakana
        self.unk_kanji_first = unk_kanji_first
        self.unk_kanji_char = unk_kanji_char
        self.unk_hiragana = unk_hiragana

    def clone(self) -> "JapaneseUnigramTokenizerFactory":
        c = object.__new__(type(self))
        TokenizerFactory.__init__(c)
        c._pre = self._pre
        c.max_word_len = self.max_word_len
        c._logtot = self._logtot
        c._log = dict(self._log)
        c.unk_katakana = self.unk_katakana
        c.unk_kanji_first = self.unk_kanji_first
        c.unk_kanji_char = self.unk_kanji_char
        c.unk_hiragana = self.unk_hiragana
        if getattr(self, "_base_log", None) is not None:
            c._base_log = dict(self._base_log)
        return c

    def add_word(self, word: str) -> None:
        """User-dictionary injection at a frequency that beats the best
        competing split (same mechanism as the zh factory). Kana/kanji
        words only — others can never match and warn+skip."""
        if len(word) < 2:
            return
        if any(_char_block(c) not in ("han", "hiragana", "katakana")
               and c not in "ー々" for c in word):
            # ー/々 extend kanji/kana runs in the Viterbi (人々, 時々,
            # ラーメン), so words containing them are matchable
            import warnings

            warnings.warn(
                f"user word {word!r} contains non-Japanese-script "
                "characters; the segmenter only matches kana/kanji runs, "
                "so it was skipped", stacklevel=2)
            return
        base = getattr(self, "_base_log", None)
        if base is None:
            base = self._base_log = dict(self._log)
        score = sum(self._word_score(base, w)
                    for w in self._viterbi_over(base, word))
        self._log[word] = max(self._log.get(word, -1e18),
                              score + self._logtot + 1e-9)
        self.max_word_len = max(self.max_word_len, len(word))

    def _word_score(self, logs, w):
        lg = logs.get(w)
        if lg is not None:
            return lg - self._logtot
        b = _char_block(w[0])
        if b == "katakana":
            return -self.unk_katakana
        if b == "han":
            return -(self.unk_kanji_first
                     + self.unk_kanji_char * (len(w) - 1))
        return -self.unk_hiragana

    def _viterbi(self, text: str) -> List[str]:
        return self._viterbi_over(self._log, text)

    def _viterbi_over(self, logs, text: str) -> List[str]:
        n = len(text)
        blocks = [_char_block(c) if c not in "ー々" else "same"
                  for c in text]
        # ー/々 extend whichever run they appear in
        for i, b in enumerate(blocks):
            if b == "same":
                blocks[i] = blocks[i - 1] if i else "katakana"
        # kata_start[j]: start of the maximal katakana run ending at j-1
        best = [0.0] + [-1e18] * n
        back = [0] * (n + 1)
        logtot = self._logtot
        for j in range(1, n + 1):
            # 1) lexicon words
            for L in range(1, min(self.max_word_len, j) + 1):
                w = text[j - L:j]
                lg = logs.get(w)
                if lg is not None:
                    sc = best[j - L] + lg - logtot
                    if sc > best[j]:
                        best[j], back[j] = sc, j - L
            bj = blocks[j - 1]
            # 2) unknown single char
            if bj == "hiragana":
                sc = best[j - 1] - self.unk_hiragana
                if sc > best[j]:
                    best[j], back[j] = sc, j - 1
            elif bj == "han":
                # unknown kanji n-gram (all-han window)
                i = j - 1
                while i > 0 and blocks[i - 1] == "han" and j - i < 6:
                    i -= 1
                for s in range(i, j):
                    sc = best[s] - (self.unk_kanji_first
                                    + self.unk_kanji_char * (j - s - 1))
                    if sc > best[j]:
                        best[j], back[j] = sc, s
            elif bj == "katakana":
                # maximal katakana run ending at j (only when the run
                # really ends here: groups loanwords as one token)
                if j == n or blocks[j] != "katakana":
                    i = j - 1
                    while i > 0 and blocks[i - 1] == "katakana":
                        i -= 1
                    sc = best[i] - self.unk_katakana
                    if sc > best[j]:
                        best[j], back[j] = sc, i
                # single-char fallback so the DP never dead-ends mid-run
                sc = best[j - 1] - (self.unk_katakana + 4.0)
                if sc > best[j]:
                    best[j], back[j] = sc, j - 1
        out: List[str] = []
        j = n
        while j > 0:
            out.append(text[back[j]:j])
            j = back[j]
        return out[::-1]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        i, n = 0, len(text)
        run_start = None

        def flush(end):
            if run_start is not None:
                tokens.extend(self._viterbi(text[run_start:end]))

        while i < n:
            ch = text[i]
            # ー/々 extend a run AND can start one (ーメン in line-broken
            # text, 々 after punctuation) — _viterbi_over's block scan
            # treats a leading extender as katakana, so create() must not
            # drop it as punctuation
            b = "han" if ch in "ー々" else _char_block(ch)
            if b in ("han", "hiragana", "katakana"):
                if run_start is None:
                    run_start = i
                i += 1
                continue
            flush(i)
            run_start = None
            if b in ("space", "punct"):
                i += 1
            else:  # latin/digit/hangul/etc runs emitted whole (same-block
                #    run loop — a char outside the loop's block must still
                #    advance, or non-Japanese scripts would spin forever)
                j = i
                while j < n and _char_block(text[j]) == b:
                    j += 1
                tokens.append(text[i:j])
                i = j
        flush(n)
        return Tokenizer(tokens, self._pre)


def segmentation_scores(factory: TokenizerFactory,
                        gold: Sequence[Sequence[str]],
                        sep: str = "") -> dict:
    """Word-boundary precision/recall/F1 against gold segmentations — the
    SIGHAN-bakeoff scoring convention: each sentence's tokens define
    character-offset spans over the concatenated (separator-free) text; a
    predicted span is correct iff it exactly matches a gold span. ``sep``
    joins tokens into the surface text handed to the tokenizer (" " for
    space-delimited Korean; "" for Chinese/Japanese). This is the quality
    measurement the reference's vendored analyzers were validated with
    upstream (ansj/Kuromoji corpora) and the gate for lexicon growth."""
    tp = fp = fn = 0
    for tokens in gold:
        # '+' marks an in-token morpheme boundary WITHOUT surface
        # whitespace (Korean particles: surface '비가' = gold 비 + 가) —
        # the surface drops it, the gold spans split on it
        text = sep.join(t.replace("+", "") for t in tokens)
        tokens = [part for t in tokens for part in t.split("+")]
        # align BOTH sides to the punctuation/space-free character stream
        # (and drop all-punct tokens): most tokenizers drop punctuation
        # themselves, but engines that emit it (jieba keeps ，。) must not
        # shift every downstream span offset
        def depunct(toks):
            out = ["".join(ch for ch in t
                           if _char_block(ch) not in ("space", "punct"))
                   for t in toks]
            return [t for t in out if t]

        kept = depunct(tokens)

        def spans(toks):
            out, pos = set(), 0
            for t in toks:
                out.add((pos, pos + len(t)))
                pos += len(t)
            return out

        pred = depunct(factory.create(text).get_tokens())
        g, p = spans(kept), spans(pred)
        tp += len(g & p)
        fp += len(p - g)
        fn += len(g - p)
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    return {"precision": round(precision, 4), "recall": round(recall, 4),
            "f1": round(f1, 4), "gold_words": tp + fn}


class _ScriptFallbackFactory(TokenizerFactory):
    """Shared engine-gating: external analyzer if importable → lexicon
    max-match (user lexicon merged over the built-in core vocabulary,
    cjk_lexicon.py) → Unicode-block segmentation."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None):
        super().__init__()
        base = set(self.default_lexicon())
        if lexicon:
            base.update(lexicon)  # user dictionary extends the core (ansj
            #                       user-dict mechanism)
        self._mm = MaxMatchTokenizerFactory(base) if base else None
        self._engine = self._load_engine()

    def default_lexicon(self) -> Iterable[str]:
        return ()

    def _load_engine(self):
        return None

    def _init_unigram_chain(self, lexicon, shared_unigram):
        """Shared stage selection for the dictionary-backed factories
        (zh/ja): external engine → shared unigram-Viterbi factory (user
        ``lexicon=`` words injected into a private clone at split-beating
        frequencies) → max-match over the hand core. Only the selected
        stage is constructed."""
        TokenizerFactory.__init__(self)
        lexicon = tuple(lexicon or ())
        self._mm = None
        if self._engine is not None:
            return
        if shared_unigram is not None:
            self._mm = shared_unigram
            if lexicon:  # private copy: user words must not leak across
                self._mm = self._mm.clone()
                for w in lexicon:
                    self._mm.add_word(w)
        else:
            base = set(self.default_lexicon())
            base.update(lexicon)
            self._mm = MaxMatchTokenizerFactory(base) if base else None

    def create(self, text: str) -> Tokenizer:
        if self._engine is not None:
            return Tokenizer(self._engine(text), self._pre)
        if self._mm is not None:
            t = self._mm.create(text)
            return Tokenizer(t.get_tokens(), self._pre)
        return Tokenizer(script_segment(text), self._pre)


@lru_cache(maxsize=None)
def _shared_unigram() -> Optional["UnigramTokenizerFactory"]:
    """Default zh unigram factory, built once per process: the 111k-entry
    log table costs ~100ms+ to derive, so lexicon-less factories share it
    (instances with a user ``lexicon=`` take a cheap ``clone()``)."""
    from .cjk_lexicon import chinese_freqs

    freqs = chinese_freqs()
    return UnigramTokenizerFactory(freqs) if freqs else None


class ChineseTokenizerFactory(_ScriptFallbackFactory):
    """deeplearning4j-nlp-chinese ``ChineseTokenizerFactory`` equivalent.

    Fallback chain: jieba when importable → unigram-Viterbi over the
    shipped 100k frequency dictionary (user ``lexicon=`` words injected at
    a frequency that beats their best competing split, jieba
    ``suggest_freq`` style) → max-match → Unicode blocks. Only the
    selected stage is constructed (no dead 100k-word max-match build)."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None):
        self._engine = self._load_engine(tuple(lexicon or ()))
        self._init_unigram_chain(lexicon, _shared_unigram())

    def default_lexicon(self):
        from .cjk_lexicon import CHINESE_CORE

        return CHINESE_CORE

    def _load_engine(self, lexicon=()):
        try:
            import jieba  # optional; not baked into the hosting image

            if lexicon:
                # user dictionary must win on the engine path too: a
                # private jieba.Tokenizer so user words don't leak into
                # other factories' segmentation
                tok = jieba.Tokenizer()
                for w in lexicon:
                    tok.suggest_freq(w, tune=True)
            else:
                tok = jieba
            return lambda text: [t for t in tok.cut(text) if t.strip()]
        except ImportError:
            return None


@lru_cache(maxsize=None)
def _shared_ja_unigram() -> Optional["JapaneseUnigramTokenizerFactory"]:
    """Default ja unigram factory, built once per process (same sharing
    pattern as the zh ``_shared_unigram``)."""
    from .cjk_lexicon import japanese_freqs

    freqs = japanese_freqs()
    return JapaneseUnigramTokenizerFactory(freqs) if freqs else None


class JapaneseTokenizerFactory(_ScriptFallbackFactory):
    """deeplearning4j-nlp-japanese (Kuromoji) equivalent.

    Fallback chain: fugashi/MeCab when importable → unigram-Viterbi over
    the shipped frequency lexicon (conjugated surfaces are first-class
    entries, so inflected text segments correctly; user ``lexicon=``
    words injected at a split-beating frequency) → max-match over the
    hand core → Unicode blocks."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None):
        self._engine = self._load_engine()
        self._init_unigram_chain(lexicon, _shared_ja_unigram())

    def default_lexicon(self):
        from .cjk_lexicon import JAPANESE_CORE

        return JAPANESE_CORE

    def _load_engine(self):
        try:
            import fugashi  # optional MeCab wrapper

            tagger = fugashi.Tagger()
            return lambda text: [w.surface for w in tagger(text) if w.surface.strip()]
        except ImportError:
            return None


class KoreanMorphemeTokenizerFactory(TokenizerFactory):
    """Lexicon-scored eojeol-internal morpheme splitting — the r5
    replacement for the bare josa suffix heuristic (r4 VERDICT #4,
    reference: deeplearning4j-nlp-korean KoreanTokenizer → OpenKoreanText,
    whose tokenizer also scores candidate (stem, josa) parses against a
    noun dictionary).

    Per eojeol (space-delimited hangul run), three candidate parses are
    scored and the best wins:

    - WHOLE, known: ``log f(eojeol) - log total`` (protects nouns whose
      surface merely *ends* in a particle char — 회의, 아이, 구두 — the
      class of systematic errors a suffix heuristic cannot avoid);
    - WHOLE, unknown: ``-(unk_stem_first + unk_stem_char·(L-1))`` — the
      default for verb/adjective eojeols, whose endings stay attached per
      the convention (full verbal morphology needs konlpy, used when
      importable);
    - SPLIT stem + one trailing particle (longest-match from the particle
      inventory, compounds like 에서/에는/까지 first): stem scored like a
      whole (known or unknown), particle costs ``particle_cost``.

    Penalties are tuned on tests/data/cjk_dev_ko.txt (an r5-authored dev
    set) — never on the r4 gold."""

    #: case/topic particles + copulas splittable off an eojeol tail.
    PARTICLES = ("에서는", "에서", "으로", "부터", "까지", "에게",
                 "한테", "처럼", "보다", "마다", "에는", "와의", "과의",
                 "입니다", "이지만", "이다", "이에요", "예요",
                 "은", "는", "이", "가", "을", "를", "의", "에", "도",
                 "만", "와", "과", "로", "께")

    def __init__(self, freqs: "Optional[dict]" = None,
                 unk_stem_first: float = 10.0,
                 unk_stem_char: float = 3.5,
                 particle_cost: float = 2.0):
        super().__init__()
        import math

        if freqs is None:
            from .cjk_lexicon import korean_freqs

            freqs = korean_freqs()
        self._logtot = math.log(max(sum(freqs.values()), 1))
        self._log = {w: math.log(f) for w, f in freqs.items() if f > 0}
        self.unk_stem_first = unk_stem_first
        self.unk_stem_char = unk_stem_char
        self.particle_cost = particle_cost

    def clone(self) -> "KoreanMorphemeTokenizerFactory":
        c = object.__new__(type(self))
        TokenizerFactory.__init__(c)
        c._pre = self._pre
        c._logtot = self._logtot
        c._log = dict(self._log)
        c.unk_stem_first = self.unk_stem_first
        c.unk_stem_char = self.unk_stem_char
        c.particle_cost = self.particle_cost
        return c

    def add_word(self, word: str) -> None:
        """Register a noun so WHOLE-known beats any false particle split
        (and so real splits of ``word+josa`` eojeols see a known stem)."""
        if not word or any(_char_block(c) != "hangul" for c in word):
            import warnings

            warnings.warn(f"user word {word!r} is not hangul; the Korean "
                          "morpheme splitter only scores hangul eojeols, "
                          "so it was skipped", stacklevel=2)
            return
        # beating a split means out-scoring stem+particle; the strongest
        # competitor is a known prefix-stem, so inject just above it
        need = max((self._log.get(word[:-len(p)], -1e18)
                    for p in self.PARTICLES if word.endswith(p)
                    and len(word) > len(p)), default=-1e18)
        self._log[word] = max(self._log.get(word, -1e18), need + 1e-9,
                              self._logtot - 8.0)

    def _stem_score(self, w: str) -> float:
        lg = self._log.get(w)
        if lg is not None:
            return lg - self._logtot
        return -(self.unk_stem_first + self.unk_stem_char * (len(w) - 1))

    def split_eojeol(self, e: str) -> List[str]:
        best_score = self._stem_score(e)
        best = [e]
        for p in self.PARTICLES:
            if e.endswith(p) and len(e) > len(p):
                stem = e[:-len(p)]
                sc = self._stem_score(stem) - self.particle_cost
                if sc > best_score:
                    best_score, best = sc, [stem, p]
        return best

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for tok in text.split():
            run: List[str] = []
            for ch in tok:
                b = _char_block(ch)
                if b == "hangul":
                    run.append(ch)
                else:
                    if run:
                        tokens.extend(self.split_eojeol("".join(run)))
                        run.clear()
                    if b not in ("space", "punct"):
                        tokens.append(ch)
            if run:
                tokens.extend(self.split_eojeol("".join(run)))
        # merge adjacent non-hangul singles back into runs (latin/digits)
        merged: List[str] = []
        for t in tokens:
            if (merged and len(t) == 1 and _char_block(t) == "latin"
                    and _char_block(merged[-1][-1]) == "latin"
                    and all(_char_block(c) == "latin" for c in merged[-1])):
                merged[-1] += t
            else:
                merged.append(t)
        return Tokenizer(merged, self._pre)


@lru_cache(maxsize=None)
def _shared_ko_morph() -> Optional["KoreanMorphemeTokenizerFactory"]:
    """Default ko morpheme factory, built once per process."""
    from .cjk_lexicon import korean_freqs

    freqs = korean_freqs()
    return KoreanMorphemeTokenizerFactory(freqs) if freqs else None


# Josa (case/topic particle) suffixes for the no-deps Korean fallback:
# compound forms first (longest match), then single-char. Genuinely
# ambiguous single-char splits are accepted as the cost of morpheme-level
# tokens (measured on tests/data/cjk_gold_ko.txt: F1 0.95 vs the morpheme
# gold; pure eojeol mode scores 0.48 against the same gold because every
# particle stays attached).
_KO_PARTICLES_LONG = ("에서는", "에서", "으로", "부터", "까지", "에게",
                      "한테", "처럼", "보다", "마다", "에는", "와의",
                      "과의", "입니다", "이지만", "이다")
_KO_PARTICLES_1 = tuple("은는이가을를의에도만와과로")


class KoreanTokenizerFactory(_ScriptFallbackFactory):
    """deeplearning4j-nlp-korean (OpenKoreanText) equivalent. Hangul is
    space-delimited into eojeol units; ``split_particles`` (default True —
    the reference's analyzer emits morphemes) additionally splits trailing
    josa particles / copulas off each eojeol. Since r5 the split is
    lexicon-scored (:class:`KoreanMorphemeTokenizerFactory` over the
    shipped ``data/ko_lexicon.txt``) rather than a bare suffix heuristic,
    so nouns that merely end in a particle character (회의, 아이) stay
    whole; the suffix heuristic remains as the lexicon-less fallback.
    Full morphological analysis needs konlpy, used when importable."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 split_particles: bool = True):
        self.split_particles = split_particles
        # NOTE: the user lexicon deliberately does NOT feed the
        # _ScriptFallbackFactory max-match base — with no default ko core
        # that base would cover ONLY the user words and shatter every
        # other eojeol into single chars. Korean eojeols come from
        # whitespace (script_segment); user words go into the morpheme
        # splitter, where they belong.
        super().__init__(None)
        self._morph = None
        if self._engine is None and split_particles:
            self._morph = _shared_ko_morph()
            if self._morph is not None and lexicon:
                self._morph = self._morph.clone()
                for w in lexicon:
                    self._morph.add_word(w)

    def _load_engine(self):
        try:
            import konlpy.tag  # optional

            okt = konlpy.tag.Okt()
            return lambda text: okt.morphs(text)
        except ImportError:
            return None

    @staticmethod
    def _split_josa(tok: str) -> List[str]:
        for p in _KO_PARTICLES_LONG:
            if tok.endswith(p) and len(tok) > len(p):
                return [tok[:-len(p)], p]
        for p in _KO_PARTICLES_1:
            if tok.endswith(p) and len(tok) > 1:
                return [tok[:-1], p]
        return [tok]

    def create(self, text: str) -> Tokenizer:
        t = super().create(text)
        if self._engine is not None or not self.split_particles:
            return t
        out: List[str] = []
        for tok in t.get_tokens():
            if tok and _char_block(tok[0]) == "hangul":
                if self._morph is not None:
                    out.extend(self._morph.split_eojeol(tok))
                else:
                    out.extend(self._split_josa(tok))
            else:
                out.append(tok)
        return Tokenizer(out, self._pre)
