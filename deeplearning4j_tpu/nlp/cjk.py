"""CJK tokenization — language packs (SURVEY.md §2.5).

Reference parity: deeplearning4j-nlp-chinese (vendored ansj segmenter, 9.5k
LoC), -japanese (Kuromoji, 6.9k), -korean (OpenKoreanText wrapper). Those
vendor full morphological analyzers; the TPU build ships:

- ``MaxMatchTokenizerFactory`` — dictionary-driven forward maximum matching
  (the classic CJK segmentation baseline; ansj's core strategy) with a
  user-supplied lexicon + single-char fallback,
- ``ChineseTokenizerFactory`` / ``JapaneseTokenizerFactory`` /
  ``KoreanTokenizerFactory`` — script-aware defaults: use jieba / fugashi /
  an external analyzer when importable (same gating the reference applies to
  its vendored engines), else fall back to max-match over an optional
  lexicon, else Unicode-block segmentation (han chars split singly, kana/
  hangul runs kept, Latin/digits as words).

All produce the shared ``Tokenizer`` interface, so Word2Vec/TF-IDF pipelines
are language-agnostic exactly like the reference's TokenizerFactory SPI.
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, List, Optional, Sequence, Set

from .tokenization import Tokenizer, TokenizerFactory


def _char_block(ch: str) -> str:
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF:
        return "han"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or 0x31F0 <= o <= 0x31FF:
        return "katakana"
    if 0xAC00 <= o <= 0xD7AF:
        return "hangul"
    if ch.isalnum():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


def script_segment(text: str) -> List[str]:
    """Unicode-block segmentation: han chars emitted singly (each hanzi is a
    morpheme-ish unit), kana/hangul/latin runs kept together, punctuation and
    whitespace dropped."""
    out: List[str] = []
    run: List[str] = []
    run_block = ""

    def flush():
        if run:
            out.append("".join(run))
            run.clear()

    for ch in text:
        b = _char_block(ch)
        if b in ("space", "punct"):
            flush()
            run_block = ""
        elif b == "han":
            flush()
            out.append(ch)
            run_block = ""
        else:
            if b != run_block:
                flush()
                run_block = b
            run.append(ch)
    flush()
    return out


class MaxMatchTokenizerFactory(TokenizerFactory):
    """Forward maximum matching over a lexicon; unmatched CJK chars emit
    singly, unmatched Latin runs emit as words."""

    def __init__(self, lexicon: Iterable[str], max_word_len: int = 8):
        super().__init__()
        self.lexicon: Set[str] = set(lexicon)
        self.max_word_len = max(max_word_len,
                                max((len(w) for w in self.lexicon), default=1))

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        i, n = 0, len(text)
        while i < n:
            ch = text[i]
            b = _char_block(ch)
            if b == "space" or b == "punct":
                i += 1
                continue
            if b == "latin":
                j = i
                while j < n and _char_block(text[j]) == "latin":
                    j += 1
                tokens.append(text[i:j])
                i = j
                continue
            matched = None
            for L in range(min(self.max_word_len, n - i), 1, -1):
                cand = text[i:i + L]
                if cand in self.lexicon:
                    matched = cand
                    break
            if matched:
                tokens.append(matched)
                i += len(matched)
            else:
                tokens.append(ch)
                i += 1
        return Tokenizer(tokens, self._pre)


def segmentation_scores(factory: TokenizerFactory,
                        gold: Sequence[Sequence[str]],
                        sep: str = "") -> dict:
    """Word-boundary precision/recall/F1 against gold segmentations — the
    SIGHAN-bakeoff scoring convention: each sentence's tokens define
    character-offset spans over the concatenated (separator-free) text; a
    predicted span is correct iff it exactly matches a gold span. ``sep``
    joins tokens into the surface text handed to the tokenizer (" " for
    space-delimited Korean; "" for Chinese/Japanese). This is the quality
    measurement the reference's vendored analyzers were validated with
    upstream (ansj/Kuromoji corpora) and the gate for lexicon growth."""
    tp = fp = fn = 0
    for tokens in gold:
        text = sep.join(tokens)
        # tokenizers DROP punctuation/space characters; align gold offsets to
        # the retained character stream (and drop all-punct gold tokens) so a
        # punctuated gold corpus scores correctly
        kept = [
            "".join(ch for ch in t
                    if _char_block(ch) not in ("space", "punct"))
            for t in tokens]
        kept = [t for t in kept if t]

        def spans(toks):
            out, pos = set(), 0
            for t in toks:
                out.add((pos, pos + len(t)))
                pos += len(t)
            return out

        pred = list(factory.create(text).get_tokens())
        g, p = spans(kept), spans(pred)
        tp += len(g & p)
        fp += len(p - g)
        fn += len(g - p)
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    return {"precision": round(precision, 4), "recall": round(recall, 4),
            "f1": round(f1, 4), "gold_words": tp + fn}


class _ScriptFallbackFactory(TokenizerFactory):
    """Shared engine-gating: external analyzer if importable → lexicon
    max-match (user lexicon merged over the built-in core vocabulary,
    cjk_lexicon.py) → Unicode-block segmentation."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None):
        super().__init__()
        base = set(self.default_lexicon())
        if lexicon:
            base.update(lexicon)  # user dictionary extends the core (ansj
            #                       user-dict mechanism)
        self._mm = MaxMatchTokenizerFactory(base) if base else None
        self._engine = self._load_engine()

    def default_lexicon(self) -> Iterable[str]:
        return ()

    def _load_engine(self):
        return None

    def create(self, text: str) -> Tokenizer:
        if self._engine is not None:
            return Tokenizer(self._engine(text), self._pre)
        if self._mm is not None:
            t = self._mm.create(text)
            return Tokenizer(t.get_tokens(), self._pre)
        return Tokenizer(script_segment(text), self._pre)


class ChineseTokenizerFactory(_ScriptFallbackFactory):
    """deeplearning4j-nlp-chinese ``ChineseTokenizerFactory`` equivalent."""

    def default_lexicon(self):
        from .cjk_lexicon import CHINESE_CORE

        return CHINESE_CORE

    def _load_engine(self):
        try:
            import jieba  # optional; not baked into the hosting image

            return lambda text: [t for t in jieba.cut(text) if t.strip()]
        except ImportError:
            return None


class JapaneseTokenizerFactory(_ScriptFallbackFactory):
    """deeplearning4j-nlp-japanese (Kuromoji) equivalent."""

    def default_lexicon(self):
        from .cjk_lexicon import JAPANESE_CORE

        return JAPANESE_CORE

    def _load_engine(self):
        try:
            import fugashi  # optional MeCab wrapper

            tagger = fugashi.Tagger()
            return lambda text: [w.surface for w in tagger(text) if w.surface.strip()]
        except ImportError:
            return None


class KoreanTokenizerFactory(_ScriptFallbackFactory):
    """deeplearning4j-nlp-korean (OpenKoreanText) equivalent. Hangul is
    space-delimited in normal text, so the block fallback already yields
    eojeol units; a lexicon refines them to morpheme-ish tokens."""

    def _load_engine(self):
        try:
            import konlpy.tag  # optional

            okt = konlpy.tag.Okt()
            return lambda text: okt.morphs(text)
        except ImportError:
            return None
