"""Japanese conjugation paradigms (ipadic 活用型) — surface-form expansion.

The reference vendors Kuromoji with the full ipadic binary dictionary
(deeplearning4j-nlp-japanese/, com.atilika.kuromoji; the dictionary itself
stores every conjugated surface as its own entry — that is how MeCab-family
analyzers handle inflection). This module reproduces that design choice in
data-light form: given a dictionary form and its ipadic conjugation class
(活用型, e.g. ``五段・カ行イ音便``), generate the conjugated SURFACE forms so
the unigram-Viterbi segmenter (cjk.py) can match inflected text without a
morphological lattice.

Paradigms are standard school-grammar tables (public knowledge; the same
tables ipadic's own ``*.csv`` entries are generated from):

- 五段 (godan) verbs: one row per consonant column, plus the euphonic-change
  (音便) stem used before た/て — イ音便 (書く→書い), 促音便 (勝つ→勝っ),
  撥音便 (読む→読ん).
- 一段 (ichidan) verbs: drop る, invariant stem.
- カ変 (来る) / サ変 (する): suppletive forms.
- 形容詞 (i-adjectives): く/かっ/けれ stems; per the segmentation convention
  used by the gold sets (and this framework's JapaneseTokenizerFactory),
  the adjective past ``〜かった`` is emitted FUSED (one token), while verb
  た/て stay separate tokens — so adjectives also generate the fused
  ``かった``/``くなかった`` surfaces.

Only surfaces are produced — no POS lattice, no connection-cost matrix;
the unigram model treats each generated form as an independent entry at a
discounted frequency of its base form's corpus count.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

# 五段 ending tables: conj_type -> (dictionary ending, [conjugated endings],
# onbin stem ending used before た/て). Conjugated endings cover 未然形
# (negative stem), 連用形 (masu stem), 終止/連体 (dictionary), 仮定形,
# 命令形, 未然ウ接続 (volitional stem); the onbin form is the surface that
# precedes た/て (emitted as its own entry — た/て are separate tokens).
_GODAN: Dict[str, Tuple[str, List[str], str]] = {
    "五段・カ行イ音便": ("く", ["か", "き", "く", "け", "こ"], "い"),
    "五段・カ行促音便": ("く", ["か", "き", "く", "け", "こ"], "っ"),  # 行く
    "五段・ガ行": ("ぐ", ["が", "ぎ", "ぐ", "げ", "ご"], "い"),
    "五段・サ行": ("す", ["さ", "し", "す", "せ", "そ"], "し"),
    "五段・タ行": ("つ", ["た", "ち", "つ", "て", "と"], "っ"),
    "五段・ナ行": ("ぬ", ["な", "に", "ぬ", "ね", "の"], "ん"),
    "五段・バ行": ("ぶ", ["ば", "び", "ぶ", "べ", "ぼ"], "ん"),
    "五段・マ行": ("む", ["ま", "み", "む", "め", "も"], "ん"),
    "五段・ラ行": ("る", ["ら", "り", "る", "れ", "ろ"], "っ"),
    "五段・ラ行アル": ("る", ["ら", "り", "る", "れ", "ろ"], "っ"),  # ある
    "五段・ワ行促音便": ("う", ["わ", "い", "う", "え", "お"], "っ"),
    "五段・ワ行ウ音便": ("う", ["わ", "い", "う", "え", "お"], "う"),  # 問う
}

# i-adjective endings: dictionary 〜い; stems: 〜く (adverbial/te-form base),
# 〜かっ (past base), 〜けれ (conditional), bare stem (〜さ/〜そう attach).
# Fused per-convention surfaces: かった, くなかった (see module docstring).
_ADJ_TYPES = ("形容詞・アウオ段", "形容詞・イ段", "形容詞・イイ")


def expand(base: str, conj_type: str) -> List[str]:
    """All conjugated surface forms for ``base`` under ipadic class
    ``conj_type`` (including ``base`` itself). Unknown classes return just
    the base — expansion is best-effort breadth, not a validator."""
    out = [base]
    g = _GODAN.get(conj_type)
    if g is not None:
        end, rows, onbin = g
        if base.endswith(end):
            stem = base[:-len(end)]
            out += [stem + e for e in rows] + [stem + onbin]
        return _dedup(out)
    if conj_type == "一段" or conj_type.startswith("一段・"):
        if base.endswith("る"):
            stem = base[:-1]
            # stem serves 未然/連用 (見, 起き); ろ/よ imperative
            out += [stem, stem + "れ", stem + "ろ", stem + "よ"]
        return _dedup(out)
    if conj_type.startswith("カ変"):
        k = base[:-2]
        if base.endswith("来る"):
            out += [k + s for s in ("来", "来い", "来れ")]
        elif base.endswith("くる"):
            out += [k + s for s in ("き", "こ", "こい", "くれ")]
        return _dedup(out)
    if conj_type.startswith("サ変"):
        if base.endswith("する"):
            stem = base[:-2]
            out += [stem + s for s in ("し", "さ", "せ", "すれ", "しろ", "せよ")]
        elif base.endswith("ずる"):
            stem = base[:-2]
            out += [stem + s for s in ("じ", "ぜ", "ずれ", "じろ")]
        return _dedup(out)
    if conj_type in _ADJ_TYPES:
        if base.endswith("い"):
            stem = base[:-1]
            if conj_type == "形容詞・イイ" and base.endswith("いい"):
                stem = base[:-2] + "よ"  # いい→よく/よかった
            out += [stem + s for s in
                    ("く", "かっ", "かった", "けれ", "ければ",
                     "くて", "くない", "くなかった")]
        return _dedup(out)
    return _dedup(out)


def _dedup(xs: Iterable[str]) -> List[str]:
    seen, out = set(), []
    for x in xs:
        if x and x not in seen:
            seen.add(x)
            out.append(x)
    return out
