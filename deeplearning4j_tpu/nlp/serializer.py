"""Word-vector serialization — parity with
``models/embeddings/loader/WordVectorSerializer.java`` (2761 LoC): the
word2vec C text + binary formats and CSV round-trips, interoperable with the
original word2vec tooling and gensim.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np


def write_word_vectors(path: str, words: List[str], vectors: np.ndarray):
    """word2vec C *text* format: header 'V D', then 'word v1 v2 ...' lines
    (WordVectorSerializer.writeWordVectors)."""
    V, D = vectors.shape
    assert len(words) == V
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{V} {D}\n")
        for w, vec in zip(words, vectors):
            f.write(w + " " + " ".join(f"{x:.6g}" for x in vec) + "\n")


def read_word_vectors(path: str) -> Tuple[List[str], np.ndarray]:
    """Inverse of write_word_vectors (WordVectorSerializer.loadTxtVectors)."""
    with open(path, "r", encoding="utf-8") as f:
        header = f.readline().split()
        V, D = int(header[0]), int(header[1])
        words: List[str] = []
        vecs = np.zeros((V, D), np.float32)
        for i in range(V):
            parts = f.readline().rstrip("\n").split(" ")
            words.append(parts[0])
            vecs[i] = np.array(parts[1:1 + D], dtype=np.float32)
    return words, vecs


def write_word2vec_binary(path: str, words: List[str], vectors: np.ndarray):
    """word2vec C *binary* format (WordVectorSerializer.writeBinary): header
    'V D\\n', then per word: 'word ' + D little-endian float32 + '\\n'."""
    V, D = vectors.shape
    with open(path, "wb") as f:
        f.write(f"{V} {D}\n".encode("utf-8"))
        for w, vec in zip(words, vectors):
            f.write(w.encode("utf-8") + b" ")
            f.write(np.asarray(vec, np.float32).tobytes())
            f.write(b"\n")


def read_word2vec_binary(path: str) -> Tuple[List[str], np.ndarray]:
    """Inverse (WordVectorSerializer.readBinaryModel)."""
    with open(path, "rb") as f:
        header = f.readline().decode("utf-8").split()
        V, D = int(header[0]), int(header[1])
        words: List[str] = []
        vecs = np.zeros((V, D), np.float32)
        for i in range(V):
            chars = bytearray()
            while True:
                c = f.read(1)
                if c in (b" ", b""):
                    break
                chars.extend(c)
            words.append(chars.decode("utf-8"))
            vecs[i] = np.frombuffer(f.read(4 * D), dtype="<f4")
            f.read(1)  # trailing newline
    return words, vecs
