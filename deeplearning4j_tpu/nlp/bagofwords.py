"""Bag-of-words / TF-IDF vectorizers — parity with the reference's
``bagofwords/vectorizer/`` (``BagOfWordsVectorizer.java``,
``TfidfVectorizer.java``).
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence

import numpy as np

from .tokenization import DefaultTokenizerFactory, TokenizerFactory
from .vocab import VocabCache, VocabConstructor


class BagOfWordsVectorizer:
    """Counts per-document term frequencies over the fitted vocab."""

    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None

    def fit(self, documents: Iterable[str]) -> "BagOfWordsVectorizer":
        token_lists = [self.tokenizer.create(d).get_tokens() for d in documents]
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False).build(token_lists)
        return self

    def transform(self, documents: Iterable[str]) -> np.ndarray:
        docs = list(documents)
        out = np.zeros((len(docs), len(self.vocab)), np.float32)
        for r, d in enumerate(docs):
            for t in self.tokenizer.create(d).get_tokens():
                i = self.vocab.index_of(t)
                if i >= 0:
                    out[r, i] += 1.0
        return out

    def fit_transform(self, documents: Sequence[str]) -> np.ndarray:
        return self.fit(documents).transform(documents)


class TfidfVectorizer(BagOfWordsVectorizer):
    """``TfidfVectorizer.java`` — tf * log(N / df) weighting (the reference
    uses the classic idf; smoothed variant selectable)."""

    def __init__(self, min_word_frequency: int = 1, smooth: bool = True,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        super().__init__(min_word_frequency, tokenizer_factory)
        self.smooth = smooth
        self.idf: Optional[np.ndarray] = None

    def fit(self, documents: Iterable[str]) -> "TfidfVectorizer":
        docs = list(documents)
        super().fit(docs)
        df = np.zeros(len(self.vocab), np.float64)
        for d in docs:
            seen = {self.vocab.index_of(t)
                    for t in self.tokenizer.create(d).get_tokens()}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        n = len(docs)
        if self.smooth:
            self.idf = np.log((1.0 + n) / (1.0 + df)) + 1.0
        else:
            self.idf = np.log(np.maximum(n / np.maximum(df, 1.0), 1.0))
        return self

    def transform(self, documents: Iterable[str]) -> np.ndarray:
        tf = super().transform(documents)
        return (tf * self.idf[None, :].astype(np.float32))
