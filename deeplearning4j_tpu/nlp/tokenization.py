"""Tokenizers, preprocessors, sentence/document iterators — parity with the
reference's ``text/tokenization/``, ``text/sentenceiterator/`` and
``text/documentiterator/`` trees (SURVEY.md §2.5).

The reference defines Tokenizer/TokenizerFactory SPIs with pluggable
preprocessors (``text/tokenization/tokenizer/TokenPreProcess.java``) and a
zoo of sentence iterators. Here the same contracts are plain Python
callables/iterables — the CJK language packs (ansj/Kuromoji vendored in the
reference, §2.5 "Language packs") are covered by the pluggable factory: wrap
any external segmenter as a ``TokenizerFactory``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, List, Optional, Sequence


class TokenPreProcess:
    """``tokenizer/TokenPreProcess.java`` — per-token normalization hook."""

    def __call__(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """``preprocessor/CommonPreprocessor.java`` — lowercase + strip
    punctuation/digits (keeps unicode letters)."""

    _STRIP = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def __call__(self, token: str) -> str:
        return self._STRIP.sub("", token).lower()


class LowCasePreprocessor(TokenPreProcess):
    def __call__(self, token: str) -> str:
        return token.lower()


class Tokenizer:
    """``tokenizer/Tokenizer.java`` — iterator over tokens of one string."""

    def __init__(self, tokens: List[str], preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = [self._pre(t) for t in self._tokens]
        return [t for t in out if t]

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self) -> Iterator[str]:
        return iter(self.get_tokens())


class TokenizerFactory:
    """``tokenizerfactory/TokenizerFactory.java`` SPI."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_preprocessor(self, pre: TokenPreProcess) -> "TokenizerFactory":
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """``DefaultTokenizerFactory.java`` — whitespace tokenization."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """``NGramTokenizerFactory.java`` — emits n-grams (joined by '_') from
    min_n to max_n over the base tokenizer's output."""

    def __init__(self, base: Optional[TokenizerFactory] = None, min_n: int = 1, max_n: int = 1):
        super().__init__()
        self.base = base or DefaultTokenizerFactory()
        self.min_n, self.max_n = min_n, max_n

    def create(self, text: str) -> Tokenizer:
        toks = self.base.create(text).get_tokens()
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(0, len(toks) - n + 1):
                out.append("_".join(toks[i:i + n]))
        return Tokenizer(out, self._pre)


# --------------------------------------------------------------------------
# Sentence / document iterators (text/sentenceiterator, text/documentiterator)
# --------------------------------------------------------------------------

class SentenceIterator:
    """``sentenceiterator/SentenceIterator.java`` — resettable stream of
    sentence strings."""

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSentenceIterator(SentenceIterator):
    """``CollectionSentenceIterator.java`` — over an in-memory collection."""

    def __init__(self, sentences: Sequence[str]):
        self.sentences = list(sentences)

    def __iter__(self):
        return iter(self.sentences)


class BasicLineIterator(SentenceIterator):
    """``BasicLineIterator.java`` — one sentence per line of a file."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


@dataclass
class LabelledDocument:
    """``documentiterator/LabelledDocument.java`` — text + label(s), the unit
    ParagraphVectors trains on."""

    content: str
    labels: List[str] = field(default_factory=list)


class LabelAwareIterator:
    """``documentiterator/LabelAwareIterator.java``."""

    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError


class CollectionLabelledIterator(LabelAwareIterator):
    def __init__(self, docs: Sequence[LabelledDocument]):
        self.docs = list(docs)

    def __iter__(self):
        return iter(self.docs)
