"""NLP / embeddings — capability parity with ``deeplearning4j-nlp-parent``
(SURVEY.md §2.5), redesigned TPU-first.

The reference trains embeddings word-at-a-time through native ``AggregateSkipGram``
/ ``AggregateCBOW`` ops (CBOW.java:166). Here training is *batched index
arrays through one jitted update step* — gather rows, compute the
negative-sampling / hierarchical-softmax objective, scatter-add sparse updates
— so the whole inner loop is a single XLA program on the MXU.
"""

from .tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    BasicLineIterator,
    CollectionSentenceIterator,
    LabelledDocument,
    CollectionLabelledIterator,
)
from .vocab import VocabWord, VocabCache, VocabConstructor, build_huffman
from .sequencevectors import SequenceVectors, SkipGram, CBOW
from .word2vec import Word2Vec
from .paragraphvectors import ParagraphVectors
from .glove import Glove, CoOccurrences
from .serializer import (
    write_word_vectors,
    read_word_vectors,
    write_word2vec_binary,
    read_word2vec_binary,
)
from .bagofwords import BagOfWordsVectorizer, TfidfVectorizer
from .iterator import CnnSentenceIterator

__all__ = [
    "CommonPreprocessor", "DefaultTokenizerFactory", "NGramTokenizerFactory",
    "BasicLineIterator", "CollectionSentenceIterator", "LabelledDocument",
    "CollectionLabelledIterator",
    "VocabWord", "VocabCache", "VocabConstructor", "build_huffman",
    "SequenceVectors", "SkipGram", "CBOW",
    "Word2Vec", "ParagraphVectors", "Glove", "CoOccurrences",
    "write_word_vectors", "read_word_vectors",
    "write_word2vec_binary", "read_word2vec_binary",
    "BagOfWordsVectorizer", "TfidfVectorizer", "CnnSentenceIterator",
]

from .cjk import (ChineseTokenizerFactory, JapaneseTokenizerFactory,
                  KoreanTokenizerFactory, MaxMatchTokenizerFactory,
                  script_segment)
__all__ += ["ChineseTokenizerFactory", "JapaneseTokenizerFactory",
            "KoreanTokenizerFactory", "MaxMatchTokenizerFactory",
            "script_segment"]

from .annotation import (Annotation, AnnotationSentenceIterator,
                         AnnotationTokenizerFactory, AnnotatorPipeline,
                         PosFilterTokenizerFactory,
                         ScriptAwareTokenizerFactory, SentenceAnnotator,
                         StemmerAnnotator, TokenizerAnnotator, porter_stem)
__all__ += ["Annotation", "AnnotationSentenceIterator",
            "AnnotationTokenizerFactory", "AnnotatorPipeline",
            "PosFilterTokenizerFactory", "ScriptAwareTokenizerFactory",
            "SentenceAnnotator", "StemmerAnnotator", "TokenizerAnnotator",
            "porter_stem"]
