"""ParagraphVectors (doc2vec) — parity with
``models/paragraphvectors/ParagraphVectors.java`` (1461 LoC) and the sequence
learning algorithms ``learning/impl/sequence/{DBOW,DM}.java``.

PV-DBOW: the document label's vector predicts each word of the document
(skip-gram with the label as the center). PV-DM: label vector + context
window mean predicts the target word (CBOW with the label mixed into the
window). Labels live in the same table as words (the reference stores them in
one lookup table too), prefixed to the vocab as special tokens.

Inference of unseen docs (``inferVector``) freezes syn1 and trains only a
fresh label row — same jitted steps with a 1-row table update.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .sequencevectors import (SequenceVectors, _cbow_ns_step,
                              _skipgram_ns_infer_step, _skipgram_ns_step)
from .tokenization import (DefaultTokenizerFactory, LabelledDocument,
                           TokenizerFactory)
from .vocab import VocabConstructor, unigram_table


class ParagraphVectors:
    def __init__(self, min_word_frequency: int = 1, layer_size: int = 100,
                 window_size: int = 5, negative_sample: int = 5,
                 learning_rate: float = 0.025, epochs: int = 1,
                 batch_size: int = 2048, seed: int = 42, dm: bool = False,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.negative_sample = max(negative_sample, 1)  # NS only (DL4J default path)
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.dm = dm
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.labels: List[str] = []
        self.vocab = None
        self.sv: Optional[SequenceVectors] = None

    def fit(self, docs: Iterable[LabelledDocument]) -> List[float]:
        docs = list(docs)
        token_lists = [self.tokenizer.create(d.content).get_tokens() for d in docs]
        self.labels = sorted({lab for d in docs for lab in d.labels})
        label_tokens = [f"__label__{l}" for l in self.labels]
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False).build(token_lists, special_tokens=label_tokens)
        self.sv = SequenceVectors(
            self.vocab, layer_size=self.layer_size, window=self.window_size,
            negative=self.negative_sample, learning_rate=self.learning_rate,
            epochs=1, batch_size=self.batch_size, seed=self.seed)
        rng = np.random.default_rng(self.seed)
        losses = []
        for _ in range(self.epochs):
            centers, contexts = [], []
            cb_tgt, cb_ctx, cb_msk = [], [], []
            W = 2 * self.window_size + 1
            for d, toks in zip(docs, token_lists):
                widx = np.array([self.vocab.index_of(t) for t in toks
                                 if t in self.vocab], dtype=np.int64)
                if not len(widx):
                    continue
                for lab in d.labels:
                    li = self.vocab.index_of(f"__label__{lab}")
                    if self.dm:
                        # PV-DM: window + label -> target
                        for i in range(len(widx)):
                            lo = max(0, i - self.window_size)
                            hi = min(len(widx), i + self.window_size + 1)
                            c = np.concatenate([widx[lo:i], widx[i + 1:hi], [li]])[:W]
                            pad = np.zeros(W, np.int64); m = np.zeros(W, np.float32)
                            pad[:len(c)] = c; m[:len(c)] = 1.0
                            cb_tgt.append(widx[i]); cb_ctx.append(pad); cb_msk.append(m)
                    else:
                        # PV-DBOW: label -> every word
                        centers.append(np.full(len(widx), li))
                        contexts.append(widx)
            ep_loss, nb = 0.0, 0
            if self.dm:
                tgt = np.asarray(cb_tgt); ctx = np.stack(cb_ctx); msk = np.stack(cb_msk)
                order = rng.permutation(len(tgt))
                tgt, ctx, msk = tgt[order], ctx[order], msk[order]
                for s in range(0, len(tgt), self.batch_size):
                    bt, bc, bm = self.sv._pad_batch3(
                        tgt[s:s + self.batch_size], ctx[s:s + self.batch_size],
                        msk[s:s + self.batch_size])
                    neg = rng.choice(len(self.vocab), size=(len(bt), self.negative_sample),
                                     p=self.sv._neg_probs)
                    self.sv.syn0, self.sv.syn1, loss = _cbow_ns_step(
                        self.sv.syn0, self.sv.syn1, jnp.asarray(bc), jnp.asarray(bm),
                        jnp.asarray(bt), jnp.asarray(neg), self.learning_rate)
                    ep_loss += float(loss); nb += 1
            else:
                cen = np.concatenate(centers); con = np.concatenate(contexts)
                order = rng.permutation(len(cen))
                cen, con = cen[order], con[order]
                for s in range(0, len(cen), self.batch_size):
                    bc = self.sv._pad_batch(cen[s:s + self.batch_size])
                    bx = self.sv._pad_batch(con[s:s + self.batch_size])
                    neg = rng.choice(len(self.vocab), size=(len(bc), self.negative_sample),
                                     p=self.sv._neg_probs)
                    self.sv.syn0, self.sv.syn1, loss = _skipgram_ns_step(
                        self.sv.syn0, self.sv.syn1, jnp.asarray(bc), jnp.asarray(bx),
                        jnp.asarray(neg), self.learning_rate)
                    ep_loss += float(loss); nb += 1
            losses.append(ep_loss / max(nb, 1))
        return losses

    # -- lookup ------------------------------------------------------------

    def get_label_vector(self, label: str) -> Optional[np.ndarray]:
        idx = self.vocab.index_of(f"__label__{label}")
        return None if idx < 0 else self.sv.vector(idx)

    def similarity(self, label_a: str, label_b: str) -> float:
        ia = self.vocab.index_of(f"__label__{label_a}")
        ib = self.vocab.index_of(f"__label__{label_b}")
        return self.sv.similarity(ia, ib)

    def infer_vector(self, text: str, steps: int = 20,
                     learning_rate: float = 0.025) -> np.ndarray:
        """``ParagraphVectors.inferVector`` — train a fresh doc vector against
        the frozen tables."""
        toks = self.tokenizer.create(text).get_tokens()
        widx = np.array([self.vocab.index_of(t) for t in toks if t in self.vocab],
                        dtype=np.int64)
        rng = np.random.default_rng(self.seed)
        D = self.layer_size
        vec = jnp.asarray((rng.random((1, D), dtype=np.float32) - 0.5) / D)
        if not len(widx):
            return np.asarray(vec[0])
        for _ in range(steps):
            neg = rng.choice(len(self.vocab), size=(len(widx), self.negative_sample),
                             p=self.sv._neg_probs)
            vec = _skipgram_ns_infer_step(
                vec, self.sv.syn1, jnp.asarray(widx), jnp.asarray(neg),
                learning_rate)
        return np.asarray(vec[0])

    def nearest_labels(self, text: str, top_n: int = 5) -> List[Tuple[str, float]]:
        v = self.infer_vector(text)
        out = []
        for lab in self.labels:
            lv = self.get_label_vector(lab)
            den = np.linalg.norm(v) * np.linalg.norm(lv)
            out.append((lab, float(v @ lv / den) if den > 0 else 0.0))
        return sorted(out, key=lambda t: -t[1])[:top_n]
