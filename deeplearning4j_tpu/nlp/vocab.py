"""Vocabulary construction + Huffman coding — parity with the reference's
``models/word2vec/wordstore/VocabConstructor.java:167`` (buildJointVocabulary),
``VocabularyHolder.java`` and the Huffman tree built for hierarchical softmax.

TPU-first twist: the vocab emits *padded index tensors* (codes/points with an
explicit length mask) so hierarchical softmax runs as one fixed-shape batched
XLA op instead of per-word variable-length loops.
"""

from __future__ import annotations

import heapq
import itertools
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class VocabWord:
    """``models/word2vec/VocabWord.java`` — element + frequency + HS codes."""

    word: str
    count: int = 0
    index: int = -1
    codes: List[int] = field(default_factory=list)   # Huffman bits (0/1)
    points: List[int] = field(default_factory=list)  # inner-node indices
    is_label: bool = False                           # ParagraphVectors labels


class VocabCache:
    """``wordstore/VocabCache.java`` — word <-> index <-> frequency store."""

    def __init__(self):
        self.words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_count = 0

    def add(self, vw: VocabWord):
        vw.index = len(self.words)
        self.words.append(vw)
        self._by_word[vw.word] = vw

    def __contains__(self, word: str) -> bool:
        return word in self._by_word

    def __len__(self) -> int:
        return len(self.words)

    def word_for(self, index: int) -> str:
        return self.words[index].word

    def index_of(self, word: str) -> int:
        vw = self._by_word.get(word)
        return -1 if vw is None else vw.index

    def get(self, word: str) -> Optional[VocabWord]:
        return self._by_word.get(word)

    def counts(self) -> np.ndarray:
        return np.array([w.count for w in self.words], dtype=np.int64)


def build_huffman(cache: VocabCache) -> int:
    """Build the Huffman tree over word frequencies and store (codes, points)
    on each VocabWord — the reference does this in ``Huffman.java`` applied by
    ``VocabConstructor``. Returns max code length."""
    n = len(cache.words)
    if n == 0:
        return 0
    if n == 1:
        cache.words[0].codes, cache.words[0].points = [0], [0]
        return 1
    counter = itertools.count()
    # heap of (count, tiebreak, node_id); leaves are 0..n-1, inner n..2n-2
    heap = [(w.count, next(counter), i) for i, w in enumerate(cache.words)]
    heapq.heapify(heap)
    parent = np.zeros(2 * n - 1, dtype=np.int64)
    binary = np.zeros(2 * n - 1, dtype=np.int8)
    next_inner = n
    while len(heap) > 1:
        c1, _, i1 = heapq.heappop(heap)
        c2, _, i2 = heapq.heappop(heap)
        parent[i1] = next_inner
        parent[i2] = next_inner
        binary[i2] = 1
        heapq.heappush(heap, (c1 + c2, next(counter), next_inner))
        next_inner += 1
    root = next_inner - 1
    max_len = 0
    for i, w in enumerate(cache.words):
        codes: List[int] = []
        points: List[int] = []
        node = i
        while node != root:
            codes.append(int(binary[node]))
            points.append(int(parent[node] - n))  # inner-node index in [0, n-1)
            node = int(parent[node])
        codes.reverse()
        points.reverse()
        w.codes, w.points = codes, points
        max_len = max(max_len, len(codes))
    return max_len


def huffman_tensors(cache: VocabCache, max_len: Optional[int] = None
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack per-word (codes, points) into padded ``(V, L)`` int arrays plus a
    ``(V, L)`` float mask — fixed shapes for the jitted HS objective."""
    L = max_len or max((len(w.codes) for w in cache.words), default=0)
    V = len(cache.words)
    codes = np.zeros((V, L), dtype=np.int32)
    points = np.zeros((V, L), dtype=np.int32)
    mask = np.zeros((V, L), dtype=np.float32)
    for i, w in enumerate(cache.words):
        k = min(len(w.codes), L)
        codes[i, :k] = w.codes[:k]
        points[i, :k] = w.points[:k]
        mask[i, :k] = 1.0
    return codes, points, mask


class VocabConstructor:
    """``wordstore/VocabConstructor.java`` — count tokens over sources, prune
    below ``min_word_frequency``, index by descending frequency, build the
    Huffman tree. (The reference parallelises counting across threads; the
    Python Counter over a token stream is IO-bound here, and training — the
    hot path — is all on-device.)"""

    def __init__(self, min_word_frequency: int = 1, build_huffman_tree: bool = True):
        self.min_word_frequency = min_word_frequency
        self.build_huffman_tree = build_huffman_tree

    def build(self, token_stream: Iterable[Sequence[str]],
              special_tokens: Sequence[str] = ()) -> VocabCache:
        counts: Counter = Counter()
        total = 0
        for tokens in token_stream:
            counts.update(tokens)
            total += len(tokens)
        cache = VocabCache()
        for tok in special_tokens:
            vw = VocabWord(word=tok, count=max(counts.pop(tok, 1), 1), is_label=True)
            cache.add(vw)
        for word, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if c >= self.min_word_frequency:
                cache.add(VocabWord(word=word, count=c))
        cache.total_count = total
        if self.build_huffman_tree:
            build_huffman(cache)
        return cache


def unigram_table(cache: VocabCache, power: float = 0.75) -> np.ndarray:
    """Negative-sampling distribution ``count^0.75`` — the reference's
    ``InMemoryLookupTable`` builds the same table (SURVEY.md §2.5 "Lookup
    tables"). Returned as normalized probabilities for ``jax.random.choice``
    rather than the reference's 100M-slot alias table."""
    c = cache.counts().astype(np.float64) ** power
    s = c.sum()
    return (c / s).astype(np.float32) if s > 0 else c.astype(np.float32)
