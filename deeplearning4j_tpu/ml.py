"""Pipeline estimator wrappers — dl4j-spark-ml equivalent (SURVEY.md §2.4:
Spark ML ``Estimator``/``Model`` wrappers, ``SparkDl4jNetwork.scala``).

The idiomatic Python counterpart of a Spark ML Pipeline stage is a
scikit-learn estimator: ``fit(X, y)`` / ``predict`` / ``predict_proba`` /
``score`` plus ``get_params``/``set_params``, so these wrappers drop into
sklearn Pipelines, GridSearchCV, and cross_val_score without depending on
sklearn itself (duck-typed contract).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class _BaseNetEstimator:
    def __init__(self, model_builder=None, epochs: int = 10, batch_size: int = 32,
                 shuffle: bool = True, seed: int = 12345, model=None):
        self.model_builder = model_builder
        self.epochs = epochs
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.model = model
        self.trainer_ = None

    # --- sklearn estimator protocol ---
    def get_params(self, deep: bool = True) -> dict:
        return {"model_builder": self.model_builder, "epochs": self.epochs,
                "batch_size": self.batch_size, "shuffle": self.shuffle,
                "seed": self.seed, "model": self.model}

    def set_params(self, **params) -> "_BaseNetEstimator":
        for k, v in params.items():
            if not hasattr(self, k):
                raise ValueError(f"Invalid parameter {k}")
            setattr(self, k, v)
        return self

    def _build(self, X, y):
        if self.model is not None:
            return self.model
        if self.model_builder is None:
            raise ValueError("pass model= or model_builder=(fn(input_shape, "
                             "n_out) -> Sequential/Graph)")
        return self.model_builder(tuple(X.shape[1:]), y.shape[-1])

    def _fit_arrays(self, X, y):
        from .data.iterators import ArrayIterator
        from .train.trainer import Trainer

        self.model = self._build(X, y)
        if self.model.params is None:
            self.model.init()
        it = ArrayIterator(np.asarray(X, np.float32), np.asarray(y, np.float32),
                           batch_size=self.batch_size, shuffle=self.shuffle,
                           seed=self.seed)
        self.trainer_ = Trainer(self.model)
        self.trainer_.fit(it, epochs=self.epochs, prefetch=False)
        return self

    def _raw_output(self, X) -> np.ndarray:
        out = self.model.output(np.asarray(X, np.float32),
                                self.trainer_.params if self.trainer_ else None,
                                self.trainer_.state if self.trainer_ else None)
        return np.asarray(out[0] if isinstance(out, list) else out)


class NeuralNetClassifier(_BaseNetEstimator):
    """sklearn-style classifier over a Sequential/Graph model.

    ``fit(X, y)`` accepts integer class labels or one-hot rows.
    """

    def fit(self, X, y) -> "NeuralNetClassifier":
        y = np.asarray(y)
        if y.ndim == 1:  # integer labels -> one-hot
            self.classes_ = np.unique(y)
            idx = np.searchsorted(self.classes_, y)
            y = np.eye(len(self.classes_), dtype=np.float32)[idx]
        else:
            self.classes_ = np.arange(y.shape[-1])
        return self._fit_arrays(np.asarray(X), y)

    def predict_proba(self, X) -> np.ndarray:
        return self._raw_output(X)

    def predict(self, X) -> np.ndarray:
        return self.classes_[np.argmax(self.predict_proba(X), axis=-1)]

    def score(self, X, y) -> float:
        """Mean accuracy (sklearn contract)."""
        y = np.asarray(y)
        if y.ndim > 1:
            y = self.classes_[np.argmax(y, axis=-1)]
        return float(np.mean(self.predict(X) == y))


class NeuralNetRegressor(_BaseNetEstimator):
    def fit(self, X, y) -> "NeuralNetRegressor":
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        return self._fit_arrays(np.asarray(X), y)

    def predict(self, X) -> np.ndarray:
        out = self._raw_output(X)
        return out[:, 0] if out.shape[-1] == 1 else out

    def score(self, X, y) -> float:
        """R^2 (sklearn contract)."""
        y = np.asarray(y, np.float64).reshape(len(np.asarray(X)), -1)
        pred = self.predict(X).reshape(y.shape)
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean(0)) ** 2).sum())
        return 1.0 - ss_res / max(ss_tot, 1e-12)
