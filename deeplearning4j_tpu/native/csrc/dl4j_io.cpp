// Native IO/ETL runtime — C++ equivalent of the reference's native-backed
// data plumbing (SURVEY.md §2.1 dataset iterators, §2.11 DataVec):
//  - Batcher: background-thread shuffled batch assembly with a bounded
//    buffer ring == AsyncDataSetIterator (deeplearning4j-nn
//    datasets/iterator/AsyncDataSetIterator.java) + the multi-consumer
//    FancyBlockingQueue idea, off the Python GIL.
//  - CSV reader == DataVec CSVRecordReader fast path.
//  - IDX reader == deeplearning4j-core datasets/mnist/MnistDbFile custom
//    binary reader.
// Exposed as a C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread dl4j_io.cpp -o libdl4j_io.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> feats;
  std::vector<float> labels;
  int64_t rows;
};

struct Batcher {
  // immutable after construction
  std::vector<float> feats;   // (n, feat_dim) row-major copy
  std::vector<float> labels;  // (n, label_dim)
  int64_t n, feat_dim, label_dim, batch_size;
  bool shuffle, drop_last;
  size_t queue_depth;

  // worker state
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<Batch> queue;
  bool epoch_done = false;   // producer finished current epoch
  bool stop = false;
  uint64_t seed = 0;
  uint64_t epoch_counter = 0;  // bumped by reset(); producer runs one epoch per bump
  uint64_t produced_epochs = 0;

  // Produces one epoch of batches. Aborts early (returning false) when the
  // consumer reset() mid-epoch (epoch_counter moved past my_gen) so stale
  // old-epoch batches never land in the freshly cleared queue.
  bool produce_epoch(uint64_t ep_seed, uint64_t my_gen) {
    std::vector<int64_t> order(n);
    for (int64_t i = 0; i < n; ++i) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(ep_seed);
      for (int64_t i = n - 1; i > 0; --i) {
        std::uniform_int_distribution<int64_t> d(0, i);
        std::swap(order[i], order[d(rng)]);
      }
    }
    for (int64_t start = 0; start < n; start += batch_size) {
      int64_t rows = std::min(batch_size, n - start);
      if (rows < batch_size && drop_last) break;
      Batch b;
      b.rows = rows;
      b.feats.resize(static_cast<size_t>(rows) * feat_dim);
      b.labels.resize(static_cast<size_t>(rows) * label_dim);
      for (int64_t r = 0; r < rows; ++r) {
        int64_t src = order[start + r];
        std::memcpy(b.feats.data() + r * feat_dim, feats.data() + src * feat_dim,
                    sizeof(float) * feat_dim);
        std::memcpy(b.labels.data() + r * label_dim,
                    labels.data() + src * label_dim, sizeof(float) * label_dim);
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] {
          return queue.size() < queue_depth || stop || epoch_counter != my_gen;
        });
        if (stop) return false;
        if (epoch_counter != my_gen) return false;  // reset() superseded us
        queue.push_back(std::move(b));
      }
      cv_get.notify_one();
    }
    return true;
  }

  void run() {
    for (;;) {
      uint64_t my_epoch, my_seed, my_gen;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] { return stop || produced_epochs < epoch_counter; });
        if (stop) return;
        // always produce the NEWEST requested epoch; intermediate requests
        // (rapid reset() calls) are skipped, matching the consumer's intent
        my_epoch = epoch_counter - 1;
        my_gen = epoch_counter;
        my_seed = seed + my_epoch;
      }
      bool completed = produce_epoch(my_seed, my_gen);
      {
        std::unique_lock<std::mutex> lk(mu);
        if (stop) return;
        // an aborted epoch is abandoned; catch produced_epochs up to the
        // generation we were producing so the next wait starts the new one
        produced_epochs = my_epoch + 1;
        if (completed && produced_epochs == epoch_counter) epoch_done = true;
      }
      cv_get.notify_all();
    }
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// npz batch-directory streamer — native fast path for export-based training
// (data/iterators.py export_batches / FileDataSetIterator): parses the
// uncompressed-zip .npz files numpy's savez writes (ZIP_STORED members) and
// prefetches upcoming batches on a background thread, off the Python GIL.
// The reference's equivalent is ExistingMiniBatchDataSetIterator over
// AsyncDataSetIterator (both Java-thread-backed).
// ---------------------------------------------------------------------------

#include <dirent.h>

#include <algorithm>
#include <fstream>
#include <string>

namespace {

struct NpyMember {
  int64_t data_offset = -1;  // absolute byte offset of raw f32 data
  int64_t ndim = 0;
  int64_t dims[8] = {0};
  int64_t nelem = 0;
  bool present() const { return data_offset >= 0; }
};

struct NpzFileInfo {
  std::string path;
  NpyMember feats, labels, fmask, lmask;
};

static uint16_t rd16(const unsigned char* p) { return p[0] | (p[1] << 8); }
static uint32_t rd32(const unsigned char* p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | (uint32_t(p[3]) << 24);
}

// Parse one member's npy header at `local_off` (zip local header offset).
// Returns false on unsupported layout (compressed member, non-f32 dtype,
// fortran order) or on a corrupt/hostile shape whose element count overflows
// or exceeds what the file can physically hold — callers treat that file as
// unreadable. `file_size` bounds the data region.
static bool parse_member(std::ifstream& f, int64_t local_off, int64_t file_size,
                         NpyMember* out) {
  unsigned char lh[30];
  f.seekg(local_off);
  f.read(reinterpret_cast<char*>(lh), 30);
  if (!f || rd32(lh) != 0x04034b50) return false;
  if (rd16(lh + 8) != 0) return false;  // compression: STORED only
  const uint16_t nlen = rd16(lh + 26), xlen = rd16(lh + 28);
  int64_t npy_off = local_off + 30 + nlen + xlen;
  unsigned char mh[12];
  f.seekg(npy_off);
  f.read(reinterpret_cast<char*>(mh), 12);
  if (!f || memcmp(mh, "\x93NUMPY", 6) != 0) return false;
  const int major = mh[6];
  int64_t hlen, hstart;
  if (major == 1) { hlen = rd16(mh + 8); hstart = npy_off + 10; }
  else { hlen = rd32(mh + 8); hstart = npy_off + 12; }
  // bound the header length BEFORE allocating: a hostile 32-bit hlen would
  // otherwise allocate ~4GB (or throw bad_alloc through the ctypes FFI
  // frame on the main thread, which has no catch and would std::terminate)
  if (hlen <= 0 || hstart + hlen > file_size) return false;
  std::string hdr(hlen, '\0');
  f.seekg(hstart);
  f.read(&hdr[0], hlen);
  if (!f) return false;
  if (hdr.find("'<f4'") == std::string::npos) return false;
  if (hdr.find("'fortran_order': True") != std::string::npos) return false;
  const size_t sp = hdr.find("'shape':");
  if (sp == std::string::npos) return false;
  const size_t po = hdr.find('(', sp), pc = hdr.find(')', po);
  if (po == std::string::npos || pc == std::string::npos) return false;
  out->ndim = 0;
  out->nelem = 1;
  std::string tup = hdr.substr(po + 1, pc - po - 1);
  size_t pos = 0;
  while (pos < tup.size() && out->ndim < 8) {
    while (pos < tup.size() && (tup[pos] == ' ' || tup[pos] == ',')) ++pos;
    if (pos >= tup.size()) break;
    int64_t v = 0;
    bool any = false;
    while (pos < tup.size() && tup[pos] >= '0' && tup[pos] <= '9') {
      if (v > (int64_t(1) << 50)) return false;  // hostile dim digits
      v = v * 10 + (tup[pos++] - '0');
      any = true;
    }
    if (!any) break;
    out->dims[out->ndim++] = v;
    if (v != 0 && out->nelem > (int64_t(1) << 50) / v) return false;  // overflow
    out->nelem *= v;
  }
  if (out->ndim == 0) return false;
  out->data_offset = hstart + hlen;
  // the claimed element count must fit in the file's remaining bytes —
  // rejects corrupt headers before any resize()/read on the prefetch thread
  if (out->nelem < 0 || out->nelem > (file_size - out->data_offset) / 4)
    return false;
  return true;
}

// Scan a .npz's zip central directory for the four known member names.
static bool parse_npz(const std::string& path, NpzFileInfo* info) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  f.seekg(0, std::ios::end);
  const int64_t size = f.tellg();
  const int64_t tail = std::min<int64_t>(size, 66000);
  std::vector<unsigned char> buf(tail);
  f.seekg(size - tail);
  f.read(reinterpret_cast<char*>(buf.data()), tail);
  int64_t eocd = -1;
  for (int64_t i = tail - 22; i >= 0; --i) {
    if (rd32(buf.data() + i) == 0x06054b50) { eocd = i; break; }
  }
  if (eocd < 0) return false;
  const uint16_t nent = rd16(buf.data() + eocd + 10);
  int64_t cd_off = rd32(buf.data() + eocd + 16);
  info->path = path;
  for (uint16_t e = 0; e < nent; ++e) {
    unsigned char ch[46];
    f.seekg(cd_off);
    f.read(reinterpret_cast<char*>(ch), 46);
    if (!f || rd32(ch) != 0x02014b50) return false;
    const uint16_t nlen = rd16(ch + 28), xlen = rd16(ch + 30), clen = rd16(ch + 32);
    std::string name(nlen, '\0');
    f.read(&name[0], nlen);
    const int64_t local_off = rd32(ch + 42);
    NpyMember* dst = nullptr;
    if (name == "features.npy") dst = &info->feats;
    else if (name == "labels.npy") dst = &info->labels;
    else if (name == "features_mask.npy") dst = &info->fmask;
    else if (name == "labels_mask.npy") dst = &info->lmask;
    if (dst && !parse_member(f, local_off, size, dst)) return false;
    cd_off += 46 + nlen + xlen + clen;
  }
  return info->feats.present() && info->labels.present();
}

struct NpzLoaded {
  int64_t idx = -1;
  std::vector<float> feats, labels, fmask, lmask;
};

struct NpzDir {
  std::vector<NpzFileInfo> files;
  // prefetch machinery (restarted by set_order)
  std::vector<int64_t> order;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<NpzLoaded> queue;
  size_t depth = 3;
  size_t next_pos = 0;   // producer cursor into `order`
  size_t in_flight = 0;  // claimed by the producer, not yet queued
  bool stop = false;
  bool failed = false;

  ~NpzDir() { join(); }

  void join() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_put.notify_all();
    cv_get.notify_all();
    if (worker.joinable()) worker.join();
  }

  static bool load_member(std::ifstream& f, const NpyMember& m,
                          std::vector<float>* out) {
    if (!m.present()) { out->clear(); return true; }
    out->resize(m.nelem);
    f.seekg(m.data_offset);
    f.read(reinterpret_cast<char*>(out->data()), m.nelem * 4);
    return bool(f);
  }

  void run() {
    for (;;) {
      int64_t idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] { return stop || (queue.size() < depth &&
                                              next_pos < order.size()); });
        if (stop || next_pos >= order.size()) return;
        idx = order[next_pos++];
        ++in_flight;
      }
      NpzLoaded ld;
      ld.idx = idx;
      bool ok = idx >= 0 && idx < int64_t(files.size());
      if (ok) {
        // an uncaught bad_alloc/length_error on this thread would terminate
        // the whole process; surface it as the ordinary -2 read failure
        try {
          const NpzFileInfo& fi = files[idx];
          std::ifstream f(fi.path, std::ios::binary);
          ok = f && load_member(f, fi.feats, &ld.feats) &&
               load_member(f, fi.labels, &ld.labels) &&
               load_member(f, fi.fmask, &ld.fmask) &&
               load_member(f, fi.lmask, &ld.lmask);
        } catch (...) {
          ok = false;
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        --in_flight;
        if (stop) return;
        if (!ok) { failed = true; }
        else queue.push_back(std::move(ld));
      }
      cv_get.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* npzdir_create(const char* dir, const char* prefix) {
  DIR* d = opendir(dir);
  if (!d) return nullptr;
  const std::string pre = std::string(prefix) + "_";
  std::vector<std::string> names;
  while (dirent* ent = readdir(d)) {
    std::string n = ent->d_name;
    // strict match: {prefix}_NNNNNN.npz (mirror data/iterators._batch_files)
    if (n.size() != pre.size() + 10 || n.compare(0, pre.size(), pre) != 0 ||
        n.compare(n.size() - 4, 4, ".npz") != 0)
      continue;
    bool digits = true;
    for (size_t i = pre.size(); i < pre.size() + 6; ++i)
      digits &= (n[i] >= '0' && n[i] <= '9');
    if (digits) names.push_back(n);
  }
  closedir(d);
  std::sort(names.begin(), names.end());
  auto* h = new NpzDir();
  for (const auto& n : names) {
    NpzFileInfo info;
    if (!parse_npz(std::string(dir) + "/" + n, &info)) { delete h; return nullptr; }
    h->files.push_back(std::move(info));
  }
  return h;
}

int64_t npzdir_count(void* hp) {
  return hp ? int64_t(static_cast<NpzDir*>(hp)->files.size()) : -1;
}

// which: 0=features 1=labels 2=features_mask 3=labels_mask.
// Returns ndim (0 = member absent, -1 = bad args); fills dims_out (cap 8).
int64_t npzdir_shape(void* hp, int64_t file_idx, int which, int64_t* dims_out) {
  auto* h = static_cast<NpzDir*>(hp);
  if (!h || file_idx < 0 || file_idx >= int64_t(h->files.size())) return -1;
  const NpzFileInfo& fi = h->files[file_idx];
  const NpyMember* m = which == 0 ? &fi.feats : which == 1 ? &fi.labels
                       : which == 2 ? &fi.fmask : &fi.lmask;
  if (!m->present()) return 0;
  for (int64_t i = 0; i < m->ndim; ++i) dims_out[i] = m->dims[i];
  return m->ndim;
}

// (Re)start prefetching the given visit order (indices into the sorted file
// list). Restart is a full worker teardown: simple and race-free.
int npzdir_set_order(void* hp, const int64_t* order, int64_t n) {
  auto* h = static_cast<NpzDir*>(hp);
  if (!h || n < 0) return -1;
  h->join();
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.clear();
    h->order.assign(order, order + n);
    h->next_pos = 0;
    h->in_flight = 0;
    h->stop = false;
    h->failed = false;
  }
  h->worker = std::thread([h] { h->run(); });
  return 0;
}

// Pop the next prefetched batch into caller buffers (sized via npzdir_shape).
// Each *_cap is the caller buffer's size in ELEMENTS and must match the
// loaded member exactly: larger would overflow the caller's heap, smaller
// would leave uninitialized tail garbage in the caller's np.empty buffers
// (files can legally be rewritten between shape caching and iteration, e.g.
// a concurrent export_batches re-export).
// Returns the file index, -1 at end-of-order, -2 on read failure, -3 on a
// size mismatch.
int64_t npzdir_next(void* hp, float* feats, int64_t feats_cap, float* labels,
                    int64_t labels_cap, float* fmask, int64_t fmask_cap,
                    float* lmask, int64_t lmask_cap) {
  auto* h = static_cast<NpzDir*>(hp);
  if (!h) return -2;
  NpzLoaded ld;
  {
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv_get.wait(lk, [&] {
      return h->failed || !h->queue.empty() ||
             (h->next_pos >= h->order.size() && h->in_flight == 0);
    });
    if (h->failed) return -2;
    if (h->queue.empty()) return -1;  // order exhausted
    ld = std::move(h->queue.front());
    h->queue.pop_front();
  }
  h->cv_put.notify_all();
  if (int64_t(ld.feats.size()) != feats_cap ||
      int64_t(ld.labels.size()) != labels_cap ||
      (fmask && int64_t(ld.fmask.size()) != fmask_cap) ||
      (lmask && int64_t(ld.lmask.size()) != lmask_cap))
    return -3;
  memcpy(feats, ld.feats.data(), ld.feats.size() * 4);
  memcpy(labels, ld.labels.data(), ld.labels.size() * 4);
  if (fmask && !ld.fmask.empty()) memcpy(fmask, ld.fmask.data(), ld.fmask.size() * 4);
  if (lmask && !ld.lmask.empty()) memcpy(lmask, ld.lmask.data(), ld.lmask.size() * 4);
  return ld.idx;
}

void npzdir_destroy(void* hp) { delete static_cast<NpzDir*>(hp); }

}  // extern "C"

extern "C" {

void* batcher_create(const float* feats, const float* labels, int64_t n,
                     int64_t feat_dim, int64_t label_dim, int64_t batch_size,
                     int shuffle, uint64_t seed, int queue_depth,
                     int drop_last) {
  if (n <= 0 || feat_dim <= 0 || label_dim <= 0 || batch_size <= 0)
    return nullptr;
  auto* b = new Batcher();
  b->feats.assign(feats, feats + n * feat_dim);
  b->labels.assign(labels, labels + n * label_dim);
  b->n = n;
  b->feat_dim = feat_dim;
  b->label_dim = label_dim;
  b->batch_size = batch_size;
  b->shuffle = shuffle != 0;
  b->drop_last = drop_last != 0;
  b->queue_depth = queue_depth > 0 ? static_cast<size_t>(queue_depth) : 4;
  b->seed = seed;
  b->epoch_counter = 1;  // start producing the first epoch immediately
  b->worker = std::thread([b] { b->run(); });
  return b;
}

// Returns rows copied (>0), or 0 when the current epoch is exhausted.
int64_t batcher_next(void* h, float* feat_out, float* label_out) {
  auto* b = static_cast<Batcher*>(h);
  Batch batch;
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->cv_get.wait(lk, [&] {
      return !b->queue.empty() ||
             (b->epoch_done && b->produced_epochs == b->epoch_counter) ||
             b->stop;
    });
    if (b->stop) return -1;
    if (b->queue.empty()) return 0;  // epoch exhausted
    batch = std::move(b->queue.front());
    b->queue.pop_front();
  }
  b->cv_put.notify_one();
  std::memcpy(feat_out, batch.feats.data(), batch.feats.size() * sizeof(float));
  std::memcpy(label_out, batch.labels.data(),
              batch.labels.size() * sizeof(float));
  return batch.rows;
}

// Begin a new epoch (optionally reshuffled with seed+epoch).
void batcher_reset(void* h) {
  auto* b = static_cast<Batcher*>(h);
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->queue.clear();
    b->epoch_done = false;
    b->epoch_counter += 1;
  }
  b->cv_put.notify_all();
}

int64_t batcher_num_batches(void* h) {
  auto* b = static_cast<Batcher*>(h);
  return b->drop_last ? b->n / b->batch_size
                      : (b->n + b->batch_size - 1) / b->batch_size;
}

void batcher_destroy(void* h) {
  auto* b = static_cast<Batcher*>(h);
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->stop = true;
  }
  b->cv_put.notify_all();
  b->cv_get.notify_all();
  if (b->worker.joinable()) b->worker.join();
  delete b;
}

// ---------- CSV (DataVec CSVRecordReader fast path) ----------

// Count data rows (excluding skipped header). Returns -1 on open failure.
int64_t csv_count_rows(const char* path, int skip_header) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t rows = 0;
  int c, prev = '\n';
  while ((c = std::fgetc(f)) != EOF) {
    if (c == '\n') ++rows;
    prev = c;
  }
  if (prev != '\n') ++rows;  // unterminated last line
  std::fclose(f);
  return rows - (skip_header ? 1 : 0);
}

// Parse into out (rows*cols float32, row-major). Returns rows parsed, <0 on error.
int64_t csv_read(const char* path, char delim, int skip_header, float* out,
                 int64_t max_rows, int64_t cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size) + 1);
  size_t rd = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  buf[rd] = '\0';

  char* p = buf.data();
  char* end = buf.data() + rd;
  if (skip_header) {
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  int64_t row = 0;
  while (p < end && row < max_rows) {
    // skip blank lines
    if (*p == '\n' || *p == '\r') {
      ++p;
      continue;
    }
    for (int64_t c = 0; c < cols; ++c) {
      char* next = nullptr;
      out[row * cols + c] = std::strtof(p, &next);
      if (next == p) return -2;  // parse failure
      p = next;
      if (c + 1 < cols) {
        if (*p != delim) return -3;  // wrong column count
        ++p;
      }
    }
    while (p < end && *p != '\n') ++p;  // tolerate trailing \r / spaces
    if (p < end) ++p;
    ++row;
  }
  return row;
}

// ---------- IDX / MNIST binary (MnistDbFile parity) ----------

static uint32_t be32(const unsigned char* b) {
  return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
         (uint32_t(b[2]) << 8) | uint32_t(b[3]);
}

// Reads header: dims_out[0]=ndim, dims_out[1..ndim]=sizes (caller provides
// >= 5 slots; IDX ndim is validated to <= 4). Returns 0 ok.
int idx_read_header(const char* path, int64_t* dims_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4 || hdr[0] != 0 || hdr[1] != 0) {
    std::fclose(f);
    return -2;
  }
  int ndim = hdr[3];
  if (ndim < 1 || ndim > 4) {  // bounds-check the file-supplied byte: the
    std::fclose(f);            // caller's buffer is fixed-size
    return -4;
  }
  dims_out[0] = ndim;
  for (int i = 0; i < ndim; ++i) {
    unsigned char d[4];
    if (std::fread(d, 1, 4, f) != 4) {
      std::fclose(f);
      return -3;
    }
    dims_out[1 + i] = be32(d);
  }
  std::fclose(f);
  return 0;
}

// Read count u8 elements into float32 out; normalize divides by 255.
int idx_read_f32(const char* path, float* out, int64_t count, int normalize) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4) {
    std::fclose(f);
    return -2;
  }
  int ndim = hdr[3];
  std::fseek(f, 4 + 4 * ndim, SEEK_SET);
  const int64_t CHUNK = 1 << 20;
  std::vector<unsigned char> buf(CHUNK);
  int64_t done = 0;
  float scale = normalize ? 1.0f / 255.0f : 1.0f;
  while (done < count) {
    int64_t want = std::min(CHUNK, count - done);
    size_t got = std::fread(buf.data(), 1, static_cast<size_t>(want), f);
    if (got == 0) {
      std::fclose(f);
      return -3;
    }
    for (size_t i = 0; i < got; ++i) out[done + i] = buf[i] * scale;
    done += static_cast<int64_t>(got);
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"
