// Native IO/ETL runtime — C++ equivalent of the reference's native-backed
// data plumbing (SURVEY.md §2.1 dataset iterators, §2.11 DataVec):
//  - Batcher: background-thread shuffled batch assembly with a bounded
//    buffer ring == AsyncDataSetIterator (deeplearning4j-nn
//    datasets/iterator/AsyncDataSetIterator.java) + the multi-consumer
//    FancyBlockingQueue idea, off the Python GIL.
//  - CSV reader == DataVec CSVRecordReader fast path.
//  - IDX reader == deeplearning4j-core datasets/mnist/MnistDbFile custom
//    binary reader.
// Exposed as a C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread dl4j_io.cpp -o libdl4j_io.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> feats;
  std::vector<float> labels;
  int64_t rows;
};

struct Batcher {
  // immutable after construction
  std::vector<float> feats;   // (n, feat_dim) row-major copy
  std::vector<float> labels;  // (n, label_dim)
  int64_t n, feat_dim, label_dim, batch_size;
  bool shuffle, drop_last;
  size_t queue_depth;

  // worker state
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<Batch> queue;
  bool epoch_done = false;   // producer finished current epoch
  bool stop = false;
  uint64_t seed = 0;
  uint64_t epoch_counter = 0;  // bumped by reset(); producer runs one epoch per bump
  uint64_t produced_epochs = 0;

  // Produces one epoch of batches. Aborts early (returning false) when the
  // consumer reset() mid-epoch (epoch_counter moved past my_gen) so stale
  // old-epoch batches never land in the freshly cleared queue.
  bool produce_epoch(uint64_t ep_seed, uint64_t my_gen) {
    std::vector<int64_t> order(n);
    for (int64_t i = 0; i < n; ++i) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(ep_seed);
      for (int64_t i = n - 1; i > 0; --i) {
        std::uniform_int_distribution<int64_t> d(0, i);
        std::swap(order[i], order[d(rng)]);
      }
    }
    for (int64_t start = 0; start < n; start += batch_size) {
      int64_t rows = std::min(batch_size, n - start);
      if (rows < batch_size && drop_last) break;
      Batch b;
      b.rows = rows;
      b.feats.resize(static_cast<size_t>(rows) * feat_dim);
      b.labels.resize(static_cast<size_t>(rows) * label_dim);
      for (int64_t r = 0; r < rows; ++r) {
        int64_t src = order[start + r];
        std::memcpy(b.feats.data() + r * feat_dim, feats.data() + src * feat_dim,
                    sizeof(float) * feat_dim);
        std::memcpy(b.labels.data() + r * label_dim,
                    labels.data() + src * label_dim, sizeof(float) * label_dim);
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] {
          return queue.size() < queue_depth || stop || epoch_counter != my_gen;
        });
        if (stop) return false;
        if (epoch_counter != my_gen) return false;  // reset() superseded us
        queue.push_back(std::move(b));
      }
      cv_get.notify_one();
    }
    return true;
  }

  void run() {
    for (;;) {
      uint64_t my_epoch, my_seed, my_gen;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] { return stop || produced_epochs < epoch_counter; });
        if (stop) return;
        // always produce the NEWEST requested epoch; intermediate requests
        // (rapid reset() calls) are skipped, matching the consumer's intent
        my_epoch = epoch_counter - 1;
        my_gen = epoch_counter;
        my_seed = seed + my_epoch;
      }
      bool completed = produce_epoch(my_seed, my_gen);
      {
        std::unique_lock<std::mutex> lk(mu);
        if (stop) return;
        // an aborted epoch is abandoned; catch produced_epochs up to the
        // generation we were producing so the next wait starts the new one
        produced_epochs = my_epoch + 1;
        if (completed && produced_epochs == epoch_counter) epoch_done = true;
      }
      cv_get.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* batcher_create(const float* feats, const float* labels, int64_t n,
                     int64_t feat_dim, int64_t label_dim, int64_t batch_size,
                     int shuffle, uint64_t seed, int queue_depth,
                     int drop_last) {
  if (n <= 0 || feat_dim <= 0 || label_dim <= 0 || batch_size <= 0)
    return nullptr;
  auto* b = new Batcher();
  b->feats.assign(feats, feats + n * feat_dim);
  b->labels.assign(labels, labels + n * label_dim);
  b->n = n;
  b->feat_dim = feat_dim;
  b->label_dim = label_dim;
  b->batch_size = batch_size;
  b->shuffle = shuffle != 0;
  b->drop_last = drop_last != 0;
  b->queue_depth = queue_depth > 0 ? static_cast<size_t>(queue_depth) : 4;
  b->seed = seed;
  b->epoch_counter = 1;  // start producing the first epoch immediately
  b->worker = std::thread([b] { b->run(); });
  return b;
}

// Returns rows copied (>0), or 0 when the current epoch is exhausted.
int64_t batcher_next(void* h, float* feat_out, float* label_out) {
  auto* b = static_cast<Batcher*>(h);
  Batch batch;
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->cv_get.wait(lk, [&] {
      return !b->queue.empty() ||
             (b->epoch_done && b->produced_epochs == b->epoch_counter) ||
             b->stop;
    });
    if (b->stop) return -1;
    if (b->queue.empty()) return 0;  // epoch exhausted
    batch = std::move(b->queue.front());
    b->queue.pop_front();
  }
  b->cv_put.notify_one();
  std::memcpy(feat_out, batch.feats.data(), batch.feats.size() * sizeof(float));
  std::memcpy(label_out, batch.labels.data(),
              batch.labels.size() * sizeof(float));
  return batch.rows;
}

// Begin a new epoch (optionally reshuffled with seed+epoch).
void batcher_reset(void* h) {
  auto* b = static_cast<Batcher*>(h);
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->queue.clear();
    b->epoch_done = false;
    b->epoch_counter += 1;
  }
  b->cv_put.notify_all();
}

int64_t batcher_num_batches(void* h) {
  auto* b = static_cast<Batcher*>(h);
  return b->drop_last ? b->n / b->batch_size
                      : (b->n + b->batch_size - 1) / b->batch_size;
}

void batcher_destroy(void* h) {
  auto* b = static_cast<Batcher*>(h);
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->stop = true;
  }
  b->cv_put.notify_all();
  b->cv_get.notify_all();
  if (b->worker.joinable()) b->worker.join();
  delete b;
}

// ---------- CSV (DataVec CSVRecordReader fast path) ----------

// Count data rows (excluding skipped header). Returns -1 on open failure.
int64_t csv_count_rows(const char* path, int skip_header) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t rows = 0;
  int c, prev = '\n';
  while ((c = std::fgetc(f)) != EOF) {
    if (c == '\n') ++rows;
    prev = c;
  }
  if (prev != '\n') ++rows;  // unterminated last line
  std::fclose(f);
  return rows - (skip_header ? 1 : 0);
}

// Parse into out (rows*cols float32, row-major). Returns rows parsed, <0 on error.
int64_t csv_read(const char* path, char delim, int skip_header, float* out,
                 int64_t max_rows, int64_t cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size) + 1);
  size_t rd = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  buf[rd] = '\0';

  char* p = buf.data();
  char* end = buf.data() + rd;
  if (skip_header) {
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  int64_t row = 0;
  while (p < end && row < max_rows) {
    // skip blank lines
    if (*p == '\n' || *p == '\r') {
      ++p;
      continue;
    }
    for (int64_t c = 0; c < cols; ++c) {
      char* next = nullptr;
      out[row * cols + c] = std::strtof(p, &next);
      if (next == p) return -2;  // parse failure
      p = next;
      if (c + 1 < cols) {
        if (*p != delim) return -3;  // wrong column count
        ++p;
      }
    }
    while (p < end && *p != '\n') ++p;  // tolerate trailing \r / spaces
    if (p < end) ++p;
    ++row;
  }
  return row;
}

// ---------- IDX / MNIST binary (MnistDbFile parity) ----------

static uint32_t be32(const unsigned char* b) {
  return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
         (uint32_t(b[2]) << 8) | uint32_t(b[3]);
}

// Reads header: dims_out[0]=ndim, dims_out[1..ndim]=sizes (caller provides
// >= 5 slots; IDX ndim is validated to <= 4). Returns 0 ok.
int idx_read_header(const char* path, int64_t* dims_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4 || hdr[0] != 0 || hdr[1] != 0) {
    std::fclose(f);
    return -2;
  }
  int ndim = hdr[3];
  if (ndim < 1 || ndim > 4) {  // bounds-check the file-supplied byte: the
    std::fclose(f);            // caller's buffer is fixed-size
    return -4;
  }
  dims_out[0] = ndim;
  for (int i = 0; i < ndim; ++i) {
    unsigned char d[4];
    if (std::fread(d, 1, 4, f) != 4) {
      std::fclose(f);
      return -3;
    }
    dims_out[1 + i] = be32(d);
  }
  std::fclose(f);
  return 0;
}

// Read count u8 elements into float32 out; normalize divides by 255.
int idx_read_f32(const char* path, float* out, int64_t count, int normalize) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4) {
    std::fclose(f);
    return -2;
  }
  int ndim = hdr[3];
  std::fseek(f, 4 + 4 * ndim, SEEK_SET);
  const int64_t CHUNK = 1 << 20;
  std::vector<unsigned char> buf(CHUNK);
  int64_t done = 0;
  float scale = normalize ? 1.0f / 255.0f : 1.0f;
  while (done < count) {
    int64_t want = std::min(CHUNK, count - done);
    size_t got = std::fread(buf.data(), 1, static_cast<size_t>(want), f);
    if (got == 0) {
      std::fclose(f);
      return -3;
    }
    for (size_t i = 0; i < got; ++i) out[done + i] = buf[i] * scale;
    done += static_cast<int64_t>(got);
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"
