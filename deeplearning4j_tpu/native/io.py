"""ctypes bindings over the native IO runtime (csrc/dl4j_io.cpp).

- ``NativeBatchIterator`` — AsyncDataSetIterator equivalent: a C++ worker
  thread assembles shuffled batches into a bounded ring off the Python GIL
  (the reference uses a Java prefetch thread,
  ``datasets/iterator/AsyncDataSetIterator.java``).
- ``read_csv`` — DataVec CSVRecordReader fast path.
- ``read_idx`` — MNIST/EMNIST IDX binary reader (``datasets/mnist/MnistDbFile``).
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence, Tuple

import numpy as np

from ..data.iterators import DataSet, DataSetIterator
from .build import build

_lib = None


def _load():
    global _lib
    if _lib is None:
        path = build()
        lib = ctypes.CDLL(str(path))
        lib.batcher_create.restype = ctypes.c_void_p
        lib.batcher_create.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.batcher_next.restype = ctypes.c_int64
        lib.batcher_next.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.POINTER(ctypes.c_float)]
        lib.batcher_reset.argtypes = [ctypes.c_void_p]
        lib.batcher_num_batches.restype = ctypes.c_int64
        lib.batcher_num_batches.argtypes = [ctypes.c_void_p]
        lib.batcher_destroy.argtypes = [ctypes.c_void_p]
        lib.csv_count_rows.restype = ctypes.c_int64
        lib.csv_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.csv_read.restype = ctypes.c_int64
        lib.csv_read.argtypes = [ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_float),
                                 ctypes.c_int64, ctypes.c_int64]
        lib.idx_read_header.restype = ctypes.c_int
        lib.idx_read_header.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.idx_read_f32.restype = ctypes.c_int
        lib.idx_read_f32.argtypes = [ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_int64, ctypes.c_int]
        _lib = lib
    return _lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeBatchIterator(DataSetIterator):
    """Shuffled minibatch iterator whose batch assembly runs on a C++ thread.

    Epoch semantics match ArrayIterator: one pass per ``__iter__``; ``reset``
    (or re-iterating) starts a reshuffled epoch with seed+epoch.
    """

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = True, seed: int = 12345, queue_depth: int = 4,
                 drop_last: bool = False):
        lib = _load()
        f = np.ascontiguousarray(features, np.float32)
        l = np.ascontiguousarray(labels, np.float32)
        assert f.shape[0] == l.shape[0], "feature/label row mismatch"
        self._feat_shape = f.shape[1:]
        self._label_shape = l.shape[1:]
        n = f.shape[0]
        self._feat_dim = int(np.prod(self._feat_shape)) if self._feat_shape else 1
        self._label_dim = int(np.prod(self._label_shape)) if self._label_shape else 1
        self._bs = batch_size
        self._h = lib.batcher_create(
            _fptr(f.reshape(n, -1)), _fptr(l.reshape(n, -1)),
            n, self._feat_dim, self._label_dim, batch_size,
            1 if shuffle else 0, seed, queue_depth, 1 if drop_last else 0)
        if not self._h:
            raise ValueError("batcher_create failed (empty input?)")
        self._lib = lib
        self._fresh = True  # epoch 1 is produced eagerly at create

    @property
    def batch_size(self):
        return self._bs

    def __len__(self):
        return int(self._lib.batcher_num_batches(self._h))

    def reset(self):
        self._lib.batcher_reset(self._h)
        self._fresh = True

    def __iter__(self):
        if not self._fresh:
            self.reset()
        self._fresh = False
        fbuf = np.empty((self._bs, self._feat_dim), np.float32)
        lbuf = np.empty((self._bs, self._label_dim), np.float32)
        while True:
            rows = self._lib.batcher_next(self._h, _fptr(fbuf), _fptr(lbuf))
            if rows <= 0:
                return
            f = fbuf[:rows].reshape((rows,) + self._feat_shape).copy()
            l = lbuf[:rows].reshape((rows,) + self._label_shape).copy()
            yield DataSet(f, l)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.batcher_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # finalizer must never raise (interpreter shutdown)  # jaxlint: disable=broad-except
            pass


def read_csv(path: str, delim: str = ",", skip_header: bool = False,
             cols: Optional[int] = None) -> np.ndarray:
    """Parse a numeric CSV into (rows, cols) float32 via the native reader."""
    lib = _load()
    rows = lib.csv_count_rows(path.encode(), 1 if skip_header else 0)
    if rows < 0:
        raise FileNotFoundError(path)
    if cols is None:
        with open(path) as f:
            if skip_header:
                f.readline()
            first = f.readline()
        cols = first.count(delim) + 1
    out = np.empty((rows, cols), np.float32)
    got = lib.csv_read(path.encode(), delim.encode(),
                       1 if skip_header else 0, _fptr(out), rows, cols)
    if got < 0:
        raise ValueError(f"csv parse error {got} in {path}")
    return out[:got]


def read_idx(path: str, normalize: bool = True) -> np.ndarray:
    """Read an IDX (MNIST-format) file into float32, optionally /255."""
    lib = _load()
    dims = (ctypes.c_int64 * 5)()
    rc = lib.idx_read_header(path.encode(), dims)
    if rc != 0:
        raise ValueError(f"bad idx file {path} (rc={rc})")
    shape = tuple(int(dims[1 + i]) for i in range(int(dims[0])))
    out = np.empty(int(np.prod(shape)), np.float32)
    rc = lib.idx_read_f32(path.encode(), _fptr(out), out.size,
                          1 if normalize else 0)
    if rc != 0:
        raise ValueError(f"idx read error {rc} in {path}")
    return out.reshape(shape)


def _load_npz_api(lib):
    if getattr(lib, "_npz_ready", False):
        return lib
    lib.npzdir_create.restype = ctypes.c_void_p
    lib.npzdir_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.npzdir_count.restype = ctypes.c_int64
    lib.npzdir_count.argtypes = [ctypes.c_void_p]
    lib.npzdir_shape.restype = ctypes.c_int64
    lib.npzdir_shape.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int64)]
    lib.npzdir_set_order.restype = ctypes.c_int
    lib.npzdir_set_order.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int64]
    lib.npzdir_next.restype = ctypes.c_int64
    lib.npzdir_next.argtypes = [ctypes.c_void_p] + [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64] * 4
    lib.npzdir_destroy.argtypes = [ctypes.c_void_p]
    lib._npz_ready = True
    return lib


class NativeFileDataSetIterator(DataSetIterator):
    """Native fast path for exported ``.npz`` batch directories
    (``data/iterators.export_batches`` / ``FileDataSetIterator`` semantics:
    strict ``{prefix}_NNNNNN.npz`` matching, per-epoch shuffle, ``shard=
    (rank, world)`` striping) — zip/npy parsing and read-ahead happen on a
    C++ prefetch thread, off the GIL (ExistingMiniBatchDataSetIterator over
    AsyncDataSetIterator, SURVEY.md §2.1)."""

    def __init__(self, directory: str, prefix: str = "dataset",
                 shuffle: bool = False, seed: int = 0,
                 shard: Optional[Tuple[int, int]] = None):
        import os

        if not os.path.isdir(directory):
            raise FileNotFoundError(f"export directory does not exist: {directory}")
        self._lib = _load_npz_api(_load())
        self._dir = directory.encode()
        self._prefix = prefix.encode()
        # validate + collect shapes once with a throwaway handle; each
        # __iter__ opens its OWN handle so concurrent/restarted generators
        # stay independent (FileDataSetIterator drop-in semantics)
        h = self._open()
        try:
            n = self._lib.npzdir_count(h)
            self._shapes = [self._file_shapes(h, i) for i in range(n)]
        finally:
            self._lib.npzdir_destroy(h)
        self._indices = list(range(n))
        if shard is not None:
            rank, world = shard
            self._indices = self._indices[rank::world]
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def _open(self):
        h = self._lib.npzdir_create(self._dir, self._prefix)
        if not h or self._lib.npzdir_count(h) == 0:
            if h:
                self._lib.npzdir_destroy(h)
            raise ValueError(
                f"no readable '{self._prefix.decode()}_NNNNNN.npz' batches in "
                f"{self._dir.decode()} (files must be numpy savez output: "
                f"STORED zip members, float32, C order)")
        return h

    def _file_shapes(self, h, i):
        dims = (ctypes.c_int64 * 8)()
        out = []
        for which in range(4):
            nd = self._lib.npzdir_shape(h, i, which, dims)
            out.append(tuple(dims[d] for d in range(nd)) if nd > 0 else None)
        return out

    def __len__(self):
        return len(self._indices)

    def __iter__(self):
        order = np.asarray(self._indices, np.int64)
        if self.shuffle:
            order = order.copy()
            self._rng.shuffle(order)
        h = self._open()
        try:
            oc = order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
            if self._lib.npzdir_set_order(h, oc, len(order)) != 0:
                raise RuntimeError("npzdir_set_order failed")
            nullf = ctypes.cast(None, ctypes.POINTER(ctypes.c_float))
            for idx in order:
                fs, ls, fms, lms = self._shapes[idx]
                f = np.empty(fs, np.float32)
                l = np.empty(ls, np.float32)
                fm = np.empty(fms, np.float32) if fms else None
                lm = np.empty(lms, np.float32) if lms else None
                got = self._lib.npzdir_next(
                    h, _fptr(f), f.size, _fptr(l), l.size,
                    _fptr(fm) if fm is not None else nullf,
                    fm.size if fm is not None else 0,
                    _fptr(lm) if lm is not None else nullf,
                    lm.size if lm is not None else 0)
                if got == -3:
                    raise RuntimeError(
                        "native npz read: file changed size since shape "
                        "caching (concurrent re-export?); rebuild the iterator")
                if got < 0:
                    raise RuntimeError(f"native npz read failed (code {got})")
                assert got == idx, (got, idx)
                yield DataSet(f, l, fm, lm)
        finally:
            self._lib.npzdir_destroy(h)
