"""ctypes bindings over the native IO runtime (csrc/dl4j_io.cpp).

- ``NativeBatchIterator`` — AsyncDataSetIterator equivalent: a C++ worker
  thread assembles shuffled batches into a bounded ring off the Python GIL
  (the reference uses a Java prefetch thread,
  ``datasets/iterator/AsyncDataSetIterator.java``).
- ``read_csv`` — DataVec CSVRecordReader fast path.
- ``read_idx`` — MNIST/EMNIST IDX binary reader (``datasets/mnist/MnistDbFile``).
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence, Tuple

import numpy as np

from ..data.iterators import DataSet, DataSetIterator
from .build import build

_lib = None


def _load():
    global _lib
    if _lib is None:
        path = build()
        lib = ctypes.CDLL(str(path))
        lib.batcher_create.restype = ctypes.c_void_p
        lib.batcher_create.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.batcher_next.restype = ctypes.c_int64
        lib.batcher_next.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.POINTER(ctypes.c_float)]
        lib.batcher_reset.argtypes = [ctypes.c_void_p]
        lib.batcher_num_batches.restype = ctypes.c_int64
        lib.batcher_num_batches.argtypes = [ctypes.c_void_p]
        lib.batcher_destroy.argtypes = [ctypes.c_void_p]
        lib.csv_count_rows.restype = ctypes.c_int64
        lib.csv_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.csv_read.restype = ctypes.c_int64
        lib.csv_read.argtypes = [ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_float),
                                 ctypes.c_int64, ctypes.c_int64]
        lib.idx_read_header.restype = ctypes.c_int
        lib.idx_read_header.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.idx_read_f32.restype = ctypes.c_int
        lib.idx_read_f32.argtypes = [ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_int64, ctypes.c_int]
        _lib = lib
    return _lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeBatchIterator(DataSetIterator):
    """Shuffled minibatch iterator whose batch assembly runs on a C++ thread.

    Epoch semantics match ArrayIterator: one pass per ``__iter__``; ``reset``
    (or re-iterating) starts a reshuffled epoch with seed+epoch.
    """

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = True, seed: int = 12345, queue_depth: int = 4,
                 drop_last: bool = False):
        lib = _load()
        f = np.ascontiguousarray(features, np.float32)
        l = np.ascontiguousarray(labels, np.float32)
        assert f.shape[0] == l.shape[0], "feature/label row mismatch"
        self._feat_shape = f.shape[1:]
        self._label_shape = l.shape[1:]
        n = f.shape[0]
        self._feat_dim = int(np.prod(self._feat_shape)) if self._feat_shape else 1
        self._label_dim = int(np.prod(self._label_shape)) if self._label_shape else 1
        self._bs = batch_size
        self._h = lib.batcher_create(
            _fptr(f.reshape(n, -1)), _fptr(l.reshape(n, -1)),
            n, self._feat_dim, self._label_dim, batch_size,
            1 if shuffle else 0, seed, queue_depth, 1 if drop_last else 0)
        if not self._h:
            raise ValueError("batcher_create failed (empty input?)")
        self._lib = lib
        self._fresh = True  # epoch 1 is produced eagerly at create

    @property
    def batch_size(self):
        return self._bs

    def __len__(self):
        return int(self._lib.batcher_num_batches(self._h))

    def reset(self):
        self._lib.batcher_reset(self._h)
        self._fresh = True

    def __iter__(self):
        if not self._fresh:
            self.reset()
        self._fresh = False
        fbuf = np.empty((self._bs, self._feat_dim), np.float32)
        lbuf = np.empty((self._bs, self._label_dim), np.float32)
        while True:
            rows = self._lib.batcher_next(self._h, _fptr(fbuf), _fptr(lbuf))
            if rows <= 0:
                return
            f = fbuf[:rows].reshape((rows,) + self._feat_shape).copy()
            l = lbuf[:rows].reshape((rows,) + self._label_shape).copy()
            yield DataSet(f, l)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.batcher_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_csv(path: str, delim: str = ",", skip_header: bool = False,
             cols: Optional[int] = None) -> np.ndarray:
    """Parse a numeric CSV into (rows, cols) float32 via the native reader."""
    lib = _load()
    rows = lib.csv_count_rows(path.encode(), 1 if skip_header else 0)
    if rows < 0:
        raise FileNotFoundError(path)
    if cols is None:
        with open(path) as f:
            if skip_header:
                f.readline()
            first = f.readline()
        cols = first.count(delim) + 1
    out = np.empty((rows, cols), np.float32)
    got = lib.csv_read(path.encode(), delim.encode(),
                       1 if skip_header else 0, _fptr(out), rows, cols)
    if got < 0:
        raise ValueError(f"csv parse error {got} in {path}")
    return out[:got]


def read_idx(path: str, normalize: bool = True) -> np.ndarray:
    """Read an IDX (MNIST-format) file into float32, optionally /255."""
    lib = _load()
    dims = (ctypes.c_int64 * 5)()
    rc = lib.idx_read_header(path.encode(), dims)
    if rc != 0:
        raise ValueError(f"bad idx file {path} (rc={rc})")
    shape = tuple(int(dims[1 + i]) for i in range(int(dims[0])))
    out = np.empty(int(np.prod(shape)), np.float32)
    rc = lib.idx_read_f32(path.encode(), _fptr(out), out.size,
                          1 if normalize else 0)
    if rc != 0:
        raise ValueError(f"idx read error {rc} in {path}")
    return out.reshape(shape)
