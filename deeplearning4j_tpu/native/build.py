"""Build the native IO library (g++ → shared object), cached by source mtime.

The reference ships its native layer as prebuilt Maven artifacts (libnd4j via
JavaCPP); here the single-TU C++17 library compiles in ~2s on first use and
is cached beside the package (or in $DL4J_TPU_CACHE)."""

from __future__ import annotations

import os
import subprocess
import sysconfig
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).parent / "csrc" / "dl4j_io.cpp"


def _lib_path() -> Path:
    cache = os.environ.get("DL4J_TPU_CACHE")
    base = Path(cache) if cache else Path(__file__).parent / "_build"
    return base / "libdl4j_io.so"


def build(force: bool = False) -> Optional[Path]:
    """Compile if stale; returns the .so path or None when no toolchain."""
    lib = _lib_path()
    if not force and lib.exists() and lib.stat().st_mtime >= _SRC.stat().st_mtime:
        return lib
    lib.parent.mkdir(parents=True, exist_ok=True)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           str(_SRC), "-o", str(lib)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        err = getattr(e, "stderr", b"")
        raise RuntimeError(
            f"native build failed: {' '.join(cmd)}\n"
            f"{err.decode() if isinstance(err, bytes) else err}") from e
    return lib


def available() -> bool:
    try:
        return build() is not None
    except RuntimeError:
        return False
