"""Native C++ runtime — IO/ETL off the Python GIL (SURVEY.md §2.11: the
reference's native layer is libnd4j/JavaCPP artifacts; compute maps to
XLA, but the host-side data plumbing is re-implemented here in C++17)."""

from .build import available, build
from .io import NativeBatchIterator, read_csv, read_idx

__all__ = ["available", "build", "NativeBatchIterator", "read_csv", "read_idx"]
