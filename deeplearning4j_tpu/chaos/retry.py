"""Bounded retry with exponential backoff and full jitter.

The two I/O edges the serving stack cannot afford to treat as infallible —
AOT store reads and weight page-in transfers — get a shared, injectable
retry discipline instead of ad-hoc loops: capped exponential backoff with
*full* jitter (uniform over ``[0, min(cap, base * 2**attempt)]``, the
AWS-architecture result that decorrelates thundering retries better than
equal jitter), a bounded attempt budget, and a ``give_up`` list for errors
where retrying is wrong (a corrupt store entry stays corrupt).

Every outcome is counted as ``fleet_retry_total{op,outcome}`` with
``outcome`` ∈ ``retry`` (one failed attempt, will back off),
``recovered`` (succeeded after ≥1 retry), ``exhausted`` (attempt budget
spent, error re-raised) — so a dashboard can tell transient flakiness
from a dying device. Clock and RNG are injectable for deterministic
tests; nothing here imports JAX.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

_HELP = "bounded-retry outcomes by operation (retry/recovered/exhausted)"


class RetryPolicy:
    """Bounded retry: ``attempts`` total tries, full-jitter backoff.

    ``rng`` and ``sleep`` are injectable so tests can pin the jitter and
    run in zero wall-clock time. A policy is stateless across ``call``s
    and safe to share between threads (``random.Random`` is internally
    locked; the default module RNG is never used).
    """

    def __init__(self, attempts: int = 3, base_s: float = 0.05,
                 cap_s: float = 2.0, *, rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 metrics=None):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.attempts = int(attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._metrics = metrics

    def backoff_s(self, retry_index: int) -> float:
        """Full-jitter delay before retry ``retry_index`` (0-based):
        uniform over ``[0, min(cap_s, base_s * 2**retry_index)]``."""
        ceiling = min(self.cap_s, self.base_s * (2.0 ** retry_index))
        return self._rng.uniform(0.0, ceiling)

    def _count(self, metrics, op: str, outcome: str) -> None:
        m = metrics if metrics is not None else self._metrics
        if m is not None:
            m.counter("fleet_retry_total", {"op": op, "outcome": outcome},
                      help=_HELP).inc()

    def call(self, fn: Callable[[], object], *, op: str,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             give_up: Tuple[Type[BaseException], ...] = (),
             metrics=None):
        """Run ``fn`` with up to ``attempts`` tries.

        Errors in ``give_up`` propagate immediately (they win over
        ``retry_on``); errors in ``retry_on`` are retried after a
        full-jitter backoff until the attempt budget is spent, then
        re-raised. Anything else propagates on the first occurrence.
        """
        retries = 0
        while True:
            try:
                out = fn()
            except give_up:
                raise
            except retry_on:
                if retries + 1 >= self.attempts:
                    self._count(metrics, op, "exhausted")
                    raise
                self._count(metrics, op, "retry")
                self._sleep(self.backoff_s(retries))
                retries += 1
                continue
            if retries:
                self._count(metrics, op, "recovered")
            return out
