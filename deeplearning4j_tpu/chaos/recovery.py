"""Training-time failure recovery — divergence rollback and segmented
checkpoint-resume, folded into the chaos fault plane.

These tools lived in ``train/faults.py`` as a second, orphaned
fault-handling path; they now sit next to the injector they are tested
against, and the segmented fit exercises the ``train.segment`` injection
point so preemption-between-segments is a seeded CI scenario rather than
a hope. ``train.faults`` remains as an import shim.

Module-level imports stay stdlib-only (the chaos base-layer rule —
``chaos/__init__.py``): numpy/jax/optax and the train serialization
helpers load inside the methods that need them, so arming a fault plane
never drags the training stack into the process.

- :class:`DivergenceListener` — NaN/inf loss detection with configurable
  action: raise (fail fast), or restore the last good snapshot and
  continue with a reduced learning-rate scale.
- :class:`FaultTolerantFit` — checkpoint-resume wrapper: runs
  ``Trainer.fit`` in segments, persisting params/opt-state every
  segment, so a preempted process restarted with the same directory
  continues where it left off.
"""

from __future__ import annotations

import json
import math
import os

from . import faults as _faults


class TrainingDivergedException(RuntimeError):
    pass


class RecoveryListener:
    """Minimal training-listener surface (duck-typed — the fit loops only
    ever call these and read the two class flags, so the chaos layer does
    not need to import ``train.listeners`` at module scope)."""

    requires_sync = False
    snapshots_state = False

    def on_epoch_start(self, trainer, epoch):
        pass

    def on_epoch_end(self, trainer, epoch):
        pass

    def iteration_done(self, trainer, iteration, epoch, loss):
        pass


class DivergenceListener(RecoveryListener):
    """Watches the per-iteration loss; on NaN/inf either raises
    ``TrainingDivergedException`` (action='raise') or rolls the trainer back
    to the last finite-loss snapshot (action='rollback')."""

    # steers the loop from iteration_done (rollback must act before the next
    # dispatch), so the fit loops must not defer this listener's reporting
    requires_sync = True

    def __init__(self, action: str = "raise", snapshot_every: int = 10,
                 max_rollbacks: int = 3, lr_backoff: float = 0.5):
        assert action in ("raise", "rollback")
        self.action = action
        self.snapshot_every = max(snapshot_every, 1)
        self.max_rollbacks = max_rollbacks
        self.lr_backoff = lr_backoff
        self.lr_scale = 1.0
        self.rollbacks = 0
        # two-stage snapshot: the loss reported at iteration k was computed
        # from the params BEFORE that step's update, so the params captured at
        # iteration k are unvalidated until a LATER finite loss confirms them.
        # _pending holds the newest (unvalidated) capture; _snap only ever
        # holds a capture whose params a later step scored finite.
        self._pending = None
        self._snap = None

    def iteration_done(self, trainer, iteration, epoch, loss):
        import jax
        import numpy as np

        if math.isfinite(loss):
            if self._pending is not None:
                self._snap = self._pending  # validated by this finite loss
                self._pending = None
            if iteration % self.snapshot_every == 0:
                # host copies: the jitted step donates the device buffers.
                # Record whether the opt state was captured in the chained
                # (post-rollback) structure so a later restore can re-wrap.
                self._pending = (jax.tree.map(np.asarray, trainer.params),
                                 jax.tree.map(np.asarray, trainer.opt_state),
                                 getattr(trainer, "_base_tx", None) is not None)
            return
        self._pending = None  # produced this non-finite loss: poison
        if self.action == "raise" or self._snap is None:
            raise TrainingDivergedException(
                f"loss {loss} at iteration {iteration} (epoch {epoch})")
        if self.rollbacks >= self.max_rollbacks:
            raise TrainingDivergedException(
                f"diverged {self.rollbacks + 1}x despite rollbacks")
        self.rollbacks += 1
        params, opt_state, snap_chained = self._snap
        trainer.params = jax.tree.map(lambda a: a, params)
        trainer.opt_state = jax.tree.map(lambda a: a, opt_state)
        # shrink the learning rate so a deterministic replay of the same data
        # order doesn't re-diverge identically: chain a (stateless) scale
        # stage onto the optimizer and rebuild the jitted step
        import optax

        self.lr_scale *= self.lr_backoff
        if not snap_chained:
            # opt-state gains the scale stage's EmptyState; snapshots taken
            # after the first rollback already carry the chained structure
            trainer.opt_state = (trainer.opt_state,
                                 optax.scale(1.0).init(trainer.params))
        if getattr(trainer, "_base_tx", None) is None:
            trainer._base_tx = trainer.tx
        trainer.tx = optax.chain(trainer._base_tx, optax.scale(self.lr_scale))
        trainer._step_fn = None
        trainer._multi_step_fn = None
        trainer._accum_step_fn = None
        trainer._tbptt_step_fn = None


class FaultTolerantFit:
    """Segmented fit with durable progress: every ``segment_epochs`` the
    model + optimizer state land in ``directory``; a relaunched process picks
    up from the recorded epoch (orbax-style resume semantics on the simple
    zip checkpoint format). Each segment boundary passes through the
    ``train.segment`` chaos seam *before* its checkpoint lands, so a seeded
    scenario can preempt the process with the previous segment still the
    durable truth — exactly the window a real preemption hits."""

    def __init__(self, trainer, directory: str, segment_epochs: int = 1):
        self.trainer = trainer
        self.directory = directory
        self.segment_epochs = max(segment_epochs, 1)
        os.makedirs(directory, exist_ok=True)

    @property
    def _meta_path(self) -> str:
        return os.path.join(self.directory, "progress.json")

    @property
    def _ckpt_path(self) -> str:
        return os.path.join(self.directory, "fault_tolerant.zip")

    def completed_epochs(self) -> int:
        if not os.path.exists(self._meta_path):
            return 0
        with open(self._meta_path) as f:
            return int(json.load(f).get("completed_epochs", 0))

    def fit(self, iterator, epochs: int, listeners=(), prefetch: bool = True):
        from ..train.serialization import load_model, save_model

        done = self.completed_epochs()
        if done > 0 and os.path.exists(self._ckpt_path):
            _, params, state, opt_state, _ = load_model(
                self._ckpt_path, opt_state_template=self.trainer.opt_state)
            self.trainer.params = params
            self.trainer.state = state
            if opt_state is not None:
                self.trainer.opt_state = opt_state
            self.trainer.epoch = done
        while done < epochs:
            seg = min(self.segment_epochs, epochs - done)
            self.trainer.fit(iterator, epochs=seg, listeners=listeners,
                             prefetch=prefetch)
            done += seg
            if _faults.ACTIVE is not None:
                # preemption window: the segment ran but its checkpoint has
                # not landed — a relaunch must redo exactly this segment
                _faults.ACTIVE.hit("train.segment", scope=str(done))
            save_model(self._ckpt_path, self.trainer.model,
                       params=self.trainer.params, state=self.trainer.state,
                       opt_state=self.trainer.opt_state)
            with open(self._meta_path, "w") as f:
                json.dump({"completed_epochs": done}, f)
        return self.trainer
