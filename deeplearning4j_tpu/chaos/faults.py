"""Deterministic fault injection — the seeded chaos plane.

The serving stack has real failure surface: daemon dispatcher/decode loops
whose death used to be silent, store and page-in I/O that can fail or
corrupt, HTTP handlers that must answer every request. None of it can be
trusted until it can be *exercised*, deterministically, in CI — the
TensorFlow lesson (PAPERS.md arXiv 1605.08695) that fault tolerance of
long-running workers is a system property you test, not hope for.

One process-global :class:`FaultPlane` holds a seeded scenario of armed
faults against **named injection points** — host-side seams the serving
tiers expose, always *before* any device dispatch so a fired fault can
never corrupt donated buffers:

- ``aot.store_read``        — inside :meth:`~..aot.store.AotStore.get`
- ``fleet.page_in_transfer`` — the pager's drain+transfer+warm step
- ``serve.decode_step``     — top of the continuous batcher's decode tick
- ``serve.dispatch``        — top of the engine's batch dispatch
- ``http.handler``          — front-door POST handlers (serve and fleet)
- ``cluster.transport``     — the cluster router's per-replica proxy hop
- ``autoscale.spawn``       — the autoscale controller, just before it
  provisions a scale-out replica (a fired fault = a failed provision;
  the controller must survive it and retry on a later tick)
- ``elastic.step``          — the elastic trainer's per-worker
  supervision round (scope = worker id; a fired error = that worker
  crashed mid-step and stops heartbeating)
- ``elastic.resize``        — between the pre-resize checkpoint and the
  redistribution (a fired error = the coordinator died mid-resize; the
  run must resume from the just-published checkpoint)
- ``train.segment``         — a fault-tolerant fit's segment boundary,
  before the segment checkpoint lands (a fired error = preemption; the
  relaunched fit must pick up from the last durable segment)

Multi-instance seams (one router talking to N in-process replicas) can be
targeted individually: a site passes ``scope="replica-0"`` to :meth:`hit`
and a spec armed with ``scope=replica-0`` fires only there, so the cluster
smoke can partition exactly one replica while the other keeps serving.

A fired fault **raises** a configured exception, **corrupts** one byte of
the data flowing through the seam, **delays**, or **hangs** (bounded, and
released early by :func:`uninstall` so a test suite can never wedge).
Firing is deterministic: each armed spec skips its first ``after``
qualifying hits then fires ``times`` times, in injection order; ``prob``
adds seeded randomness for soak-style runs (CI scenarios keep it at 1.0).

The OFF state is the contract: ``ACTIVE`` is ``None`` until
:func:`install`, and every injection site guards with a plain
``if faults.ACTIVE is not None`` — one module-attribute load on the hot
path, **zero fault-plane calls** when disabled (spy-asserted in
``tests/test_chaos.py``), zero behavior change.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

#: The injection points the serving stack exposes. ``hit()`` accepts any
#: name (subsystems may add seams), but these are the wired-in ones.
POINTS = (
    "aot.store_read",
    "fleet.page_in_transfer",
    "serve.decode_step",
    "serve.dispatch",
    "http.handler",
    "cluster.transport",
    "autoscale.spawn",
    "elastic.step",
    "elastic.resize",
    "train.segment",
)

#: The installed plane, or None (the zero-overhead default). Injection
#: sites read this attribute and call nothing when it is None.
ACTIVE: Optional["FaultPlane"] = None

_MODES = ("error", "corrupt", "delay", "hang")

# spec-string error types (parse_spec); a Python API caller passes any
# exception type/instance directly
_ERROR_TYPES = {
    "runtime": RuntimeError,
    "os": OSError,
    "timeout": TimeoutError,
    "connection": ConnectionError,
}


class _Spec:
    """One armed fault: where, what, and how many times."""

    __slots__ = ("point", "mode", "error", "delay_s", "hang_s", "skip",
                 "remaining", "prob", "fired", "scope")

    def __init__(self, point: str, mode: str, *, error=None, delay_s=0.0,
                 hang_s=0.0, after: int = 0, times: int = 1,
                 prob: float = 1.0, scope: Optional[str] = None):
        self.point = point
        self.mode = mode
        self.scope = scope
        self.error = error
        self.delay_s = float(delay_s)
        self.hang_s = float(hang_s)
        self.skip = int(after)
        self.remaining = int(times)   # -1 = unbounded
        self.prob = float(prob)
        self.fired = 0


def parse_spec(text: str) -> Tuple[str, dict]:
    """``"point:mode[:k=v,...]"`` -> ``(point, inject-kwargs)``.

    Examples: ``aot.store_read:corrupt:times=1``,
    ``fleet.page_in_transfer:error:type=os,times=2``,
    ``serve.decode_step:hang:hang_s=5``.
    """
    parts = text.split(":")
    if len(parts) < 2:
        raise ValueError(f"fault spec {text!r} needs point:mode")
    point, mode = parts[0], parts[1]
    if mode not in _MODES:
        raise ValueError(f"unknown fault mode {mode!r}; one of {_MODES}")
    opts: Dict[str, str] = {}
    for chunk in parts[2:]:
        for item in chunk.split(","):
            if not item:
                continue
            k, _, v = item.partition("=")
            opts[k] = v
    kw: Dict[str, object] = {
        "times": int(opts.pop("times", 1)),
        "after": int(opts.pop("after", 0)),
        "prob": float(opts.pop("prob", 1.0)),
    }
    if "scope" in opts:
        kw["scope"] = opts.pop("scope")
    if mode == "error":
        name = opts.pop("type", "runtime")
        if name not in _ERROR_TYPES:
            raise ValueError(f"unknown error type {name!r}; one of "
                             f"{sorted(_ERROR_TYPES)}")
        kw["error"] = _ERROR_TYPES[name]
    elif mode == "corrupt":
        kw["corrupt"] = True
    elif mode == "delay":
        kw["delay_s"] = float(opts.pop("delay_s", 0.05))
    else:  # hang
        kw["hang_s"] = float(opts.pop("hang_s", 30.0))
    if opts:
        raise ValueError(f"unknown fault options {sorted(opts)} in {text!r}")
    return point, kw


class FaultPlane:
    """Seeded, deterministic fault scenario.

    Arm faults with :meth:`inject` (or :meth:`inject_spec` from a CLI
    string), :func:`install` the plane, run traffic, read
    :meth:`injected` / :meth:`hits` to assert the scenario actually
    exercised what it claimed to.
    """

    def __init__(self, seed: int = 0, metrics=None):
        self._rng = random.Random(int(seed))
        self._metrics = metrics
        self._lock = threading.Lock()
        self._specs: List[_Spec] = []
        self._hit_counts: Dict[str, int] = {}
        self._injected: Dict[Tuple[str, str], int] = {}
        self._unhang = threading.Event()

    # ------------------------------------------------------------------ arm
    def inject(self, point: str, *, error=None, corrupt: bool = False,
               delay_s: Optional[float] = None,
               hang_s: Optional[float] = None, times: int = 1,
               after: int = 0, prob: float = 1.0,
               scope: Optional[str] = None) -> "FaultPlane":
        """Arm one fault at ``point``. Exactly one of ``error`` (exception
        type or instance to raise), ``corrupt`` (flip one seeded byte of
        the data at the seam), ``delay_s``, or ``hang_s`` (bounded hang,
        released early by :meth:`release`). The fault skips its first
        ``after`` qualifying hits, then fires ``times`` times
        (``times=-1``: every hit); ``prob`` gates each firing on the
        plane's seeded RNG. ``scope`` narrows the fault to hits that pass
        the same scope (e.g. one replica id); ``None`` matches every hit.
        Returns self for chaining."""
        chosen = [m for m, on in (("error", error is not None),
                                  ("corrupt", corrupt),
                                  ("delay", delay_s is not None),
                                  ("hang", hang_s is not None)) if on]
        if len(chosen) != 1:
            raise ValueError("arm exactly one of error=, corrupt=True, "
                             f"delay_s=, hang_s= (got {chosen or 'none'})")
        if times == 0 or times < -1:
            raise ValueError("times must be positive or -1 (unbounded)")
        spec = _Spec(point, chosen[0], error=error, delay_s=delay_s or 0.0,
                     hang_s=hang_s or 0.0, after=after, times=times,
                     prob=prob, scope=scope)
        with self._lock:
            self._specs.append(spec)
        return self

    def inject_spec(self, text: str) -> "FaultPlane":
        """Arm from a ``point:mode[:k=v,...]`` string (CLI surface)."""
        point, kw = parse_spec(text)
        return self.inject(point, **kw)

    # ------------------------------------------------------------------ fire
    def hit(self, point: str, data: Optional[bytes] = None,
            scope: Optional[str] = None):
        """One hit on an injection point. Fires the first armed, matching
        spec (raise / delay / hang / corrupt-and-return); passes ``data``
        through untouched otherwise. Sites that move bytes pass them in
        and use the return value; control-flow sites ignore it. A site at
        a multi-instance seam passes its instance id as ``scope``;
        scoped specs only fire on a matching scope."""
        spec = None
        idx = 0
        with self._lock:
            self._hit_counts[point] = self._hit_counts.get(point, 0) + 1
            for s in self._specs:
                if s.point != point or s.remaining == 0:
                    continue
                if s.scope is not None and s.scope != scope:
                    continue
                if s.skip > 0:
                    s.skip -= 1
                    continue
                if s.prob < 1.0 and self._rng.random() >= s.prob:
                    continue
                if s.remaining > 0:
                    s.remaining -= 1
                s.fired += 1
                spec = s
                break
            if spec is not None:
                key = (point, spec.mode)
                self._injected[key] = self._injected.get(key, 0) + 1
                if spec.mode == "corrupt" and data:
                    idx = self._rng.randrange(len(data))
        if spec is None:
            return data
        if self._metrics is not None:
            self._metrics.counter(
                "chaos_faults_injected_total",
                {"point": point, "mode": spec.mode},
                help="faults fired by the installed chaos plane").inc()
        # a fired fault is forensic gold: stamp it into the flight recorder
        # ring and onto the Perfetto timeline (import deferred so the chaos
        # plane stays importable stand-alone)
        from ..obs import flight as _flight
        from ..obs import reqtrace as _rt
        if _flight.ACTIVE is not None:
            _flight.ACTIVE.record_event("fault", point, spec.mode)
        _rt.instant(f"fault:{point}", mode=spec.mode)
        if spec.mode == "error":
            exc = spec.error
            if isinstance(exc, type):
                exc = exc(f"chaos: injected fault at {point!r}")
            raise exc
        if spec.mode == "delay":
            time.sleep(spec.delay_s)
            return data
        if spec.mode == "hang":
            # bounded, and released early by uninstall()/release(): a chaos
            # hang may stall a worker, never the whole test suite
            self._unhang.wait(spec.hang_s)
            return data
        if data is None:
            return None
        buf = bytearray(data)
        if buf:
            buf[idx] ^= 0xFF
        return bytes(buf)

    # ------------------------------------------------------------ inspection
    def hits(self, point: str) -> int:
        """Total hits observed at ``point`` (fired or not)."""
        with self._lock:
            return self._hit_counts.get(point, 0)

    def injected(self) -> Dict[Tuple[str, str], int]:
        """(point, mode) -> faults actually fired."""
        with self._lock:
            return dict(self._injected)

    def release(self) -> None:
        """Wake every site currently parked in a ``hang`` fault."""
        self._unhang.set()

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": dict(self._hit_counts),
                "injected": {f"{p}:{m}": n
                             for (p, m), n in sorted(self._injected.items())},
                "armed": [{"point": s.point, "mode": s.mode,
                           "remaining": s.remaining, "fired": s.fired,
                           "scope": s.scope}
                          for s in self._specs],
            }


# ---------------------------------------------------------------- lifecycle
def install(plane: FaultPlane) -> FaultPlane:
    """Make ``plane`` the process-global fault plane."""
    global ACTIVE
    ACTIVE = plane
    return plane


def uninstall() -> Optional[FaultPlane]:
    """Disable fault injection and release any hung sites."""
    global ACTIVE
    plane, ACTIVE = ACTIVE, None
    if plane is not None:
        plane.release()
    return plane


@contextmanager
def scenario(plane: FaultPlane):
    """``with scenario(plane): ...`` — install for the block, always
    uninstall (and un-hang) on the way out."""
    install(plane)
    try:
        yield plane
    finally:
        uninstall()
