"""chaos/ — deterministic fault injection and shared resilience primitives.

Stdlib-only base layer (no JAX, no imports from other subsystems): the
seeded :class:`FaultPlane` with its named injection points, and the
:class:`RetryPolicy` that serve/, fleet/, and aot/ wrap around their
fallible I/O. Off by default; see ``chaos/README.md``.
"""

# NOTE: faults.ACTIVE is deliberately NOT re-exported — a `from` import
# would freeze the value at import time. Injection sites read it as a
# module attribute: `from ..chaos import faults` ... `faults.ACTIVE`.
from .faults import (POINTS, FaultPlane, install, parse_spec, scenario,
                     uninstall)
from .retry import RetryPolicy

__all__ = [
    "POINTS",
    "FaultPlane",
    "RetryPolicy",
    "install",
    "parse_spec",
    "scenario",
    "uninstall",
]
