#!/usr/bin/env python
"""Parse a captured .xplane.pb directly: aggregate device-plane XEvent
durations by op name and print the top self-time entries.

Usage: python scripts/parse_xplane.py <xplane.pb> [top_n]
"""

import collections
import sys


def main():
    path = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())

    for plane in xs.planes:
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        ev_meta = {m.id: m for m in plane.event_metadata.values()}
        stat_meta = {m.id: m.name for m in plane.stat_metadata.values()}
        totals = collections.Counter()
        counts = collections.Counter()
        total_all = 0
        for line in plane.lines:
            # XLA op lines: pick the line with the most events (op level)
            for ev in line.events:
                m = ev_meta.get(ev.metadata_id)
                name = m.name if m else "?"
                dur = ev.duration_ps / 1e9  # -> ms
                totals[(line.name, name)] += dur
                counts[(line.name, name)] += 1
        by_line = collections.defaultdict(collections.Counter)
        for (ln, name), d in totals.items():
            by_line[ln][name] += d
        print(f"=== plane: {plane.name} ===")
        import re

        for ln, ctr in by_line.items():
            tot = sum(ctr.values())
            print(f"--- line: {ln}  total {tot:.2f} ms over capture ---")
            if ln == "XLA Ops":
                # aggregate by op class (strip %, trailing .N, leading fused-op prefix)
                cls = collections.Counter()
                for name, d in ctr.items():
                    m = re.match(r"%?([a-zA-Z_\-]+)", name)
                    cls[m.group(1) if m else name] += d
                for name, d in cls.most_common(20):
                    print(f"  [class] {d:10.3f} ms  {name}")
            for name, d in ctr.most_common(top_n):
                print(f"{d:10.3f} ms  x{counts[(ln, name)]:<5d} {name[:140]}")


if __name__ == "__main__":
    main()
