#!/usr/bin/env python
"""Conv layout experiment (r3 floor-analysis follow-up, r4 VERDICT task 2):
does feeding XLA NCHW instead of NHWC change the conv+BN step floor on v5e?

Times isolated ResNet-50 stage blocks (conv3x3 + BN-train + relu, fwd+bwd)
under both dimension_numbers on the real chip. XLA chooses internal tilings
either way (activation layouts are compiler-picked batch-minor); this
settles with a measurement whether the NHWC choice in nn/layers/conv.py
leaves layout headroom, as named (and not run) in PERF.md's r3 floor
analysis. Timing: value-neutral carry chain + one readback (see
flashbwd_sweep.py).
"""
import sys
import time

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

from chiputil import smoke_or_probe

SMOKE = smoke_or_probe()

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ResNet-50 stage shapes (B, H, W, C_in, C_out) — stride-1 3x3 blocks, the
# bulk of the conv time (strided transition convs are a small fraction)
STAGES = ([("smoke", 2, 8, 8, 16, 16)] if SMOKE else [
    ("stage1", 128, 56, 56, 256, 256),
    ("stage2", 128, 28, 28, 512, 512),
    ("stage3", 128, 14, 14, 1024, 1024),
])


def block_nhwc(x, w, gamma, beta):
    y = lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                 dimension_numbers=("NHWC", "HWIO", "NHWC"))
    mean = jnp.mean(y, axis=(0, 1, 2), dtype=jnp.float32)
    msq = jnp.mean(lax.square(y.astype(jnp.float32)), axis=(0, 1, 2))
    var = jnp.maximum(msq - lax.square(mean), 0.0)
    a = lax.rsqrt(var + 1e-5) * gamma
    b = beta - mean * a
    return jax.nn.relu(y * a.astype(y.dtype) + b.astype(y.dtype))


def block_nchw(x, w, gamma, beta):
    y = lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                 dimension_numbers=("NCHW", "OIHW", "NCHW"))
    mean = jnp.mean(y, axis=(0, 2, 3), dtype=jnp.float32)
    msq = jnp.mean(lax.square(y.astype(jnp.float32)), axis=(0, 2, 3))
    var = jnp.maximum(msq - lax.square(mean), 0.0)
    a = (lax.rsqrt(var + 1e-5) * gamma)[None, :, None, None]
    b = (beta - mean * lax.rsqrt(var + 1e-5) * gamma)[None, :, None, None]
    return jax.nn.relu(y * a.astype(y.dtype) + b.astype(y.dtype))


def timed(layout, B, H, W, Cin, Cout, iters=8):
    rng = np.random.RandomState(0)
    if layout == "nhwc":
        x = jnp.asarray(rng.randn(B, H, W, Cin), jnp.bfloat16)
        w = jnp.asarray(rng.randn(3, 3, Cin, Cout) * 0.05, jnp.bfloat16)
        fn = block_nhwc
    else:
        x = jnp.asarray(rng.randn(B, Cin, H, W), jnp.bfloat16)
        w = jnp.asarray(rng.randn(Cout, Cin, 3, 3) * 0.05, jnp.bfloat16)
        fn = block_nchw
    gamma = jnp.ones((Cout,), jnp.float32)
    beta = jnp.zeros((Cout,), jnp.float32)

    @jax.jit
    def g(x, w, carry):
        def loss(x, w):
            return jnp.sum(fn(x + (carry * 0).astype(x.dtype), w,
                              gamma, beta).astype(jnp.float32) ** 2)
        dx, dw = jax.grad(loss, argnums=(0, 1))(x, w)
        return (jnp.sum(dx.astype(jnp.float32))
                + jnp.sum(dw.astype(jnp.float32)))

    carry = jnp.float32(0)
    carry = g(x, w, carry)
    float(carry)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = g(x, w, carry)
    float(carry)
    return (time.perf_counter() - t0) / iters * 1e3


for name, B, H, W, Cin, Cout in STAGES:
    t_nhwc = timed("nhwc", B, H, W, Cin, Cout)
    t_nchw = timed("nchw", B, H, W, Cin, Cout)
    flops = 2 * B * H * W * 9 * Cin * Cout * 3  # fwd + dx + dw
    print(f"{name} (B{B} {H}x{W} C{Cin}->{Cout}): NHWC {t_nhwc:.2f}ms "
          f"({flops/t_nhwc/1e9:.1f} TF/s)  NCHW {t_nchw:.2f}ms "
          f"({flops/t_nchw/1e9:.1f} TF/s)", flush=True)
print("DONE")
