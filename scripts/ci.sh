#!/usr/bin/env bash
# CI gate: jaxlint first (milliseconds, catches TPU-correctness bugs the
# CPU test suite cannot see), then the tier-1 pytest command from ROADMAP.md.
# Fails the build on any jaxlint finding or tier-1 regression.
set -euo pipefail
cd "$(dirname "$0")/.."

CI_ARTIFACTS_DIR="${CI_ARTIFACTS_DIR:-ci-artifacts}"
mkdir -p "$CI_ARTIFACTS_DIR"

echo "=== jaxlint: deeplearning4j_tpu/ (whole-program, SARIF) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/ \
  --sarif "$CI_ARTIFACTS_DIR/jaxlint.sarif"

# obs/ and analysis/ must stay jaxlint-clean by construction — no
# suppressions, no baseline entries permitted: telemetry that trips
# host-sync/jit-side-effect would poison the very hot paths it measures,
# and the linter linting itself dirty would be absurd. The tree-wide run
# above covers both; these explicit passes keep the guarantee visible even
# if the tree run's path set changes.
echo "=== jaxlint: deeplearning4j_tpu/obs/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/obs/
echo "=== jaxlint: deeplearning4j_tpu/analysis/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/analysis/
# serve/ is new code with no legacy debt: it must ALSO stay clean with no
# baseline — a recompile or unlocked mutation in the request path is an
# outage, so the serving tree gets the same zero-suppression bar as obs/.
echo "=== jaxlint: deeplearning4j_tpu/serve/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/serve/
# aot/ persists compiled executables across processes: a lint-dirty store
# layer (unlocked shared state, swallowed errors) would corrupt every
# replica that mounts it, so it holds the same zero-suppression bar.
echo "=== jaxlint: deeplearning4j_tpu/aot/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/aot/
# fleet/ pages model weights and multiplexes tenants: an unlocked resident
# map or a swallowed drain error serves one tenant another tenant's params,
# so it holds the same zero-suppression bar as serve/.
echo "=== jaxlint: deeplearning4j_tpu/fleet/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/fleet/
# chaos/ is the fault plane the hardening tests stand on: a lint-dirty
# injector (unlocked spec state, swallowed errors) would make every chaos
# result untrustworthy, so it holds the same zero-suppression bar.
echo "=== jaxlint: deeplearning4j_tpu/chaos/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/chaos/
# cluster/ is the one front door every replica hides behind: an unlocked
# membership map or a swallowed failover error turns one replica's death
# into a full outage, so the routing tier gets the same zero-suppression
# bar as serve/ and fleet/.
echo "=== jaxlint: deeplearning4j_tpu/cluster/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/cluster/
# sim/ decides which serving config every replica boots with: a lint-dirty
# simulator (hidden nondeterminism, swallowed errors) would tune the fleet
# against a workload that never existed, so it holds the same
# zero-suppression bar.
echo "=== jaxlint: deeplearning4j_tpu/sim/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/sim/
# autoscale/ spends money and kills replicas on its own authority: a
# lint-dirty controller (unlocked managed map, swallowed actuation errors)
# would flap the fleet it is supposed to steady, so it holds the same
# zero-suppression bar.
echo "=== jaxlint: deeplearning4j_tpu/autoscale/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/autoscale/
# elastic/ resizes the training mesh and rewrites optimizer-state layouts
# while a job is live: a lint-dirty trainer (host sync in the step loop,
# swallowed checkpoint errors) would corrupt the one artifact a crashed
# job resumes from, so it holds the same zero-suppression bar.
echo "=== jaxlint: deeplearning4j_tpu/elastic/ (no baseline permitted) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/elastic/

# The v3 concurrency family (lock-order-cycle, blocking-call-under-lock,
# acquire-release, property-vs-call, metric-docs-drift) rides every run
# above — the five serving subsystems hold it at zero findings with no
# baseline. Legacy surface modules (ui/, knn/) run against a committed
# ratchet baseline instead: currently empty (they are clean too), so the
# file exists purely to pin the ratchet — any NEW finding there fails CI,
# and the baseline may only ever shrink.
# The v4 compile-surface pass proves the serving tier's compile bound
# statically: continuous-batcher decode = exactly 1 executable, prefill =
# the committed bucket products. Any jit site whose executable-cardinality
# bound widens past scripts/compile_budget.json (new site, new symbolic
# factor, unbounded dim, numeric regression, stale budget entry) fails the
# build; tightening is always allowed. The report uploads next to the
# SARIF. The enumeration pass then expands the budget's symbolic bounds
# against the committed scripts/serve_config.json into the concrete
# prebuild manifest — smoke_serve.py compiles it into a fresh store via
# `aot prebuild --from-surface` and strict-boots a replica from it, so
# the static bound and the runtime surface are proven EQUAL every build.
echo "=== jaxlint: compile-surface budget + prebuild manifest (serve/ + nn/) ==="
python -m deeplearning4j_tpu.analysis \
  deeplearning4j_tpu/serve deeplearning4j_tpu/nn \
  --compile-surface "$CI_ARTIFACTS_DIR/compile_surface.json" \
  --budget scripts/compile_budget.json \
  --enumerate-manifest "$CI_ARTIFACTS_DIR/prebuild_manifest.json" \
  --serve-config scripts/serve_config.json

# elastic/ gets its own compile-surface gate: its one jit site (the
# ZeRO-1 pstep) dispatches through AotFunction indirection, so the
# static bound is "?" by construction and the budget's why documents
# the runtime ledger (elastic_pstep_traces_total, pinned flat after
# warm() by smoke_elastic) as the enforcing side. No prebuild manifest:
# the trainer warms its own ladder at boot.
echo "=== jaxlint: compile-surface budget (elastic/) ==="
python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/elastic \
  --compile-surface "$CI_ARTIFACTS_DIR/elastic_compile_surface.json" \
  --budget scripts/elastic_compile_budget.json

# The v5 error-surface pass proves the serving tier's error behaviour
# statically: every exception that can reach a serve/fleet/cluster HTTP
# boundary is walked interprocedurally (analysis/errorflow.py) and its
# (exception -> status / Retry-After / counted-metric) triple is diffed
# against scripts/error_budget.json. A new untyped escape, a new
# endpoint, or a typed error losing its status mapping fails the build;
# tightening always passes. The report uploads next to the SARIF.
# elastic/ rides along: it exposes no HTTP endpoints (its failures are
# typed ElasticError/chaos exceptions surfaced to the driver), so its
# presence must never widen the budget — the walk proves that.
echo "=== jaxlint: error-surface budget (serve/ + fleet/ + cluster/ + utils/ + elastic/) ==="
python -m deeplearning4j_tpu.analysis \
  deeplearning4j_tpu/serve deeplearning4j_tpu/fleet \
  deeplearning4j_tpu/cluster deeplearning4j_tpu/utils \
  deeplearning4j_tpu/elastic \
  --error-surface "$CI_ARTIFACTS_DIR/error_surface.json" \
  --error-budget scripts/error_budget.json

echo "=== jaxlint: ui/ + knn/ (ratchet baseline) ==="
python -m deeplearning4j_tpu.analysis \
  deeplearning4j_tpu/ui/ deeplearning4j_tpu/knn/ \
  --baseline scripts/jaxlint_legacy_baseline.json

echo "=== smoke trace: 5-step instrumented train ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_trace.py

echo "=== smoke serve: mixed predict/generate traffic over HTTP ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_serve.py

echo "=== smoke chaos: seeded fault scenario, self-healing fleet ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_chaos.py

echo "=== smoke cluster: kill-a-replica drill behind the router ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_cluster.py

echo "=== smoke sim: trace replay determinism + autotuned boot ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_sim.py

echo "=== smoke autoscale: burn-driven scale-out, drain-based scale-in ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_autoscale.py

echo "=== smoke elastic: chaos-kill -> reap -> reshard -> bit-identical resume ==="
CI_ARTIFACTS_DIR="$CI_ARTIFACTS_DIR" python scripts/smoke_elastic.py

# every scrape artifact the smokes wrote must be an exposition a real
# Prometheus would accept — promcheck is the gate, not just a warning
echo "=== promcheck: validate every scraped .prom artifact ==="
python -m deeplearning4j_tpu.obs.promcheck "$CI_ARTIFACTS_DIR"/*.prom

echo "=== tier-1 tests ==="
set -o pipefail
rm -f /tmp/_t1.log
# 1500s: the suite has grown past the old 870s budget (a pre-elastic run
# already logged 878s; ~1360 tests now) — keep headroom over measured time
timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
