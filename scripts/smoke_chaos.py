#!/usr/bin/env python
"""CI smoke chaos: boot a fleet server, run a seeded fault scenario against
it, and assert it SELF-HEALS — the ISSUE-8 acceptance surface.

The scenario (deterministic, seeded, armed via the chaos/ CLI spec
strings):

- **A. transient AOT corruption** — one store read is corrupted on a
  model's re-activation: the entry quarantines, the executable falls back
  to a live trace, the request still answers correctly.
- **B. transient page-in failure** — one weight transfer raises ``OSError``:
  the pager's bounded retry recovers, ``fleet_retry_total{outcome=
  "recovered"}`` counts it, tokens match the fault-free reference.
- **C. hung decode tick** — one decode step hangs for 8 s under a 0.75 s
  watchdog deadline: the in-flight generation is shed with a **typed** 503
  (``worker_stall``, never a hang), the watchdog crash-only-restarts the
  batcher, readiness returns, and the retried generation matches the
  reference exactly.
- **D. deterministic page-in failure** — every transfer for one model
  fails until its circuit breaker opens (2 consecutive): requests shed
  instantly with 503 ``breaker_open`` + ``Retry-After`` and NO new
  transfer attempts; after ``reset_s`` the half-open probe succeeds and
  the breaker closes.

The run is fully traced (ISSUE 9): a request tracer + flight recorder are
installed, so phase C's faulted generation must reconstruct its whole life
— admit -> queue -> prefill -> decode -> shed -> flush — from the flight
dump the scenario triggers, and its trace id must stitch across >= 3
distinct threads in the Perfetto export.

Final assertions: health is ``ok``, readiness is back, every error along
the way was typed (no bare 500s), the watchdog/retry/breaker counters all
moved, and no worker thread is left hanging. Artifacts:
$CI_ARTIFACTS_DIR/smoke_chaos_metrics.prom (the final /metrics scrape,
validated by obs.promcheck), smoke_chaos_trace.json (Perfetto), and the
flight_NN.json dumps the watchdog/breaker triggers wrote.
"""

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

WATCHDOG_S = 0.75
BREAKER_FAILURES = 2
BREAKER_RESET_S = 1.0
X = [[0.1, -0.2, 0.3, -0.4]]
PROMPT = [3, 1, 4, 1, 5]


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.status, r.read()


def _typed_503(port, path, body):
    """POST expecting a typed 503; returns (cause, retry_after, headers)."""
    try:
        _post(port, path, body)
    except urllib.error.HTTPError as e:
        assert e.code == 503, f"expected 503 from {path}, got {e.code}"
        payload = json.loads(e.read())
        assert "cause" in payload, f"untyped 503 from {path}: {payload}"
        return payload["cause"], e.headers.get("Retry-After"), e.headers
    raise AssertionError(f"{path} unexpectedly succeeded")


def _wait_ready(port, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            status, _ = _get(port, "/ready")
            if status == 200:
                return
        except urllib.error.HTTPError:
            pass
        time.sleep(0.1)
    raise AssertionError(f"server not ready within {timeout_s}s")


def _metric(scrape: str, name: str, **labels) -> float:
    total = 0.0
    found = False
    for line in scrape.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in "{ ":
            continue  # a longer metric name sharing this prefix
        if not all(f'{k}="{v}"' in rest for k, v in labels.items()):
            continue
        total += float(line.rsplit(" ", 1)[1])
        found = True
    assert found, f"metric {name}{labels or ''} missing from scrape"
    return total


def main():
    artifacts = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(artifacts, exist_ok=True)

    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.chaos import FaultPlane, install, uninstall
    from deeplearning4j_tpu.fleet import FleetRegistry, FleetServer
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.nn.layers import Dense, Output
    from deeplearning4j_tpu.nn.model import NetConfig, Sequential
    from deeplearning4j_tpu.obs import flight as flight_mod
    from deeplearning4j_tpu.obs import reqtrace as reqtrace_mod
    from deeplearning4j_tpu.obs.flight import FlightRecorder
    from deeplearning4j_tpu.obs.promcheck import check_text
    from deeplearning4j_tpu.obs.reqtrace import (RequestTracer,
                                                 parse_traceparent)
    from deeplearning4j_tpu.obs.trace import Tracer

    dense = Sequential(NetConfig(seed=0),
                       [Dense(n_out=6, activation="tanh"),
                        Output(n_out=3, loss="mcxent", activation="softmax")],
                       (4,))
    dense.init()
    lm = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                  num_heads=4, vocab=50).build()
    lm.init()

    store_dir = tempfile.mkdtemp(prefix="smoke_chaos_aot_")
    fleet = FleetRegistry(aot_store=AotStore(store_dir),
                          breaker_failures=BREAKER_FAILURES,
                          breaker_reset_s=BREAKER_RESET_S,
                          watchdog_s=WATCHDOG_S)
    # a budget only one model fits under, so every phase exercises a real
    # page cycle (drain the victim, transfer the incoming weights)
    d = fleet.add("d", dense)
    g = fleet.add("g", lm, gen_opts={"slots": 2, "capacity": 24, "seed": 0})
    fleet.pager.budget_bytes = (max(d.weight_bytes, g.weight_bytes)
                                + min(d.weight_bytes, g.weight_bytes) // 2)
    assert d.weight_bytes + g.weight_bytes > fleet.pager.budget_bytes

    # full observability: request tracing + a black-box flight recorder
    # dumping into the CI artifact dir on watchdog/breaker triggers
    tracer = Tracer()
    recorder = flight_mod.install(FlightRecorder(out_dir=artifacts))
    reqtrace_mod.install(RequestTracer(tracer=tracer, flight=recorder))

    srv = FleetServer(fleet, port=0).start()
    port = srv.port
    fp = install(FaultPlane(seed=0, metrics=fleet.metrics))
    try:
        gen_body = {"prompt": PROMPT, "max_new_tokens": 6,
                    "temperature": 0.0, "stream": False}

        # ---- fault-free reference pass (also populates the AOT store)
        ref_pred = _post(port, "/v1/models/d/predict", {"ndarray": X})
        ref_toks = _post(port, "/v1/models/g/generate?stream=false",
                         gen_body)["tokens"]
        _wait_ready(port)

        # ---- A: one corrupted AOT store read during d's re-activation
        print("=== phase A: transient AOT store corruption ===")
        fp.inject_spec("aot.store_read:corrupt:times=1")
        out = _post(port, "/v1/models/d/predict", {"ndarray": X})
        assert np.allclose(out["output"], ref_pred["output"]), \
            "corrupted store read changed a prediction"
        assert fp.injected().get(("aot.store_read", "corrupt")) == 1

        # ---- B: one torn page-in transfer; bounded retry recovers
        print("=== phase B: transient page-in failure (retry recovers) ===")
        fp.inject_spec("fleet.page_in_transfer:error:type=os,times=1")
        toks = _post(port, "/v1/models/g/generate?stream=false",
                     gen_body)["tokens"]
        assert toks == ref_toks, "retried page-in changed generation output"
        assert fp.injected().get(("fleet.page_in_transfer", "error")) == 1

        # ---- C: hung decode tick; watchdog sheds typed + restarts
        print("=== phase C: hung decode tick (watchdog restart) ===")
        fp.inject_spec("serve.decode_step:hang:hang_s=8,times=1")
        t0 = time.monotonic()
        cause, _, hdrs = _typed_503(
            port, "/v1/models/g/generate?stream=false", gen_body)
        assert cause == "worker_stall", f"expected worker_stall, got {cause}"
        assert time.monotonic() - t0 < 6.0, "stall shed was not prompt"
        parsed = parse_traceparent(hdrs.get("traceparent"))
        assert parsed is not None, "shed response carried no traceparent"
        faulted_trace = parsed[0]
        _wait_ready(port)  # watchdog restarted the batcher, health cleared
        toks = _post(port, "/v1/models/g/generate?stream=false",
                     gen_body)["tokens"]
        assert toks == ref_toks, "post-restart generation diverged"

        # ---- D: deterministic page-in failure opens d's breaker
        print("=== phase D: circuit breaker open -> probe -> closed ===")
        fp.inject_spec(
            f"fleet.page_in_transfer:error:type=os,times={3 * 2}")
        for _ in range(BREAKER_FAILURES):
            cause, _, _ = _typed_503(port, "/v1/models/d/predict",
                                     {"ndarray": X})
            assert cause == "page_in_failed", cause
        transfers = fp.hits("fleet.page_in_transfer")
        cause, retry_after, _ = _typed_503(port, "/v1/models/d/predict",
                                           {"ndarray": X})
        assert cause == "breaker_open", cause
        assert retry_after is not None and int(retry_after) >= 1
        assert fp.hits("fleet.page_in_transfer") == transfers, \
            "open breaker still attempted a page-in"
        status, _ = _get(port, "/health")
        assert status == 200, "degraded must stay live (not failed)"
        time.sleep(BREAKER_RESET_S + 0.3)
        out = _post(port, "/v1/models/d/predict", {"ndarray": X})  # probe
        assert np.allclose(out["output"], ref_pred["output"])
        assert fleet.status()["breakers"]["d"]["state"] == "closed"

        # ---- final: healthy, ready, every counter moved
        _wait_ready(port)
        status, body = _get(port, "/health")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok", health
        scrape = _get(port, "/metrics")[1].decode()
        with open(os.path.join(artifacts, "smoke_chaos_metrics.prom"),
                  "w") as f:
            f.write(scrape)
        assert _metric(scrape, "chaos_faults_injected_total") >= 5
        assert _metric(scrape, "serve_watchdog_stalls_total") >= 1
        assert _metric(scrape, "serve_watchdog_restarts_total") >= 1
        assert _metric(scrape, "fleet_retry_total", outcome="recovered") >= 1
        assert _metric(scrape, "fleet_breaker_transitions_total",
                       to="open") >= 1
        assert _metric(scrape, "fleet_breaker_transitions_total",
                       to="closed") >= 1
        assert _metric(scrape, "serve_http_errors_total", code="503") >= 4
        assert _metric(scrape, "serve_aot_fallback_total") >= 1
        assert _metric(scrape, "serve_health_state", component="fleet") == 0

        # ---- the scrape artifact must survive the exposition validator
        errors = check_text(scrape, openmetrics=False)
        assert not errors, f"invalid /metrics exposition: {errors[:5]}"
        om = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text"}),
            timeout=30).read().decode()
        with open(os.path.join(artifacts,
                               "smoke_chaos_metrics_om.prom"), "w") as f:
            f.write(om)
        errors = check_text(om)
        assert not errors, f"invalid OpenMetrics exposition: {errors[:5]}"
        assert '# {trace_id="' in om, "no exemplars in OpenMetrics scrape"

        # ---- black box: the dumps the scenario triggered reconstruct the
        # faulted request's whole life
        dump_paths = recorder.dumps
        assert dump_paths, "seeded scenario produced no flight dump"
        reasons = set()
        faulted_rec = None
        for p in dump_paths:
            with open(p) as f:
                body = json.load(f)
            reasons.add(body["reason"])
            for rec in body["requests"]:
                if rec["trace_id"] == faulted_trace:
                    faulted_rec = rec
        assert "watchdog_restart" in reasons, reasons
        assert "breaker_open" in reasons, reasons
        assert faulted_rec is not None, \
            "faulted request's record missing from every flight dump"
        assert faulted_rec["status"] == "error" \
            and faulted_rec["error"] == "worker_stall"
        stage_names = [s["name"] for s in faulted_rec["stages"]]
        for want in ("admit", "queue", "prefill_chunk", "decode", "shed",
                     "flush"):
            assert want in stage_names, (want, stage_names)

        # ---- Perfetto: one trace id, stitched across >= 3 threads
        trace_path = os.path.join(artifacts, "smoke_chaos_trace.json")
        tracer.export(trace_path)
        tids = {e["tid"] for e in tracer.events
                if e.get("id") == faulted_trace}
        assert len(tids) >= 3, \
            f"faulted trace crossed only {len(tids)} threads: {tids}"
        print(f"flight dumps: {sorted(reasons)}; faulted request "
              f"{faulted_rec['request_id']} reconstructed across "
              f"{len(tids)} threads")
        print("final fault-plane stats:", json.dumps(fp.stats()["injected"]))
    finally:
        uninstall()  # release any parked hang before joining workers
        srv.stop()
        reqtrace_mod.uninstall()
        flight_mod.uninstall()

    # no worker left wedged: everything the scenario stalled was either
    # restarted (and drained by stop()) or released by uninstall()
    import threading
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        hung = [t for t in threading.enumerate()
                if t.name.startswith(("serve-", "fleet-")) and t.is_alive()]
        if not hung:
            break
        time.sleep(0.1)
    assert not hung, f"worker threads left hanging: {[t.name for t in hung]}"
    print("smoke chaos OK: injected faults recovered, health ok, "
          "no hung workers")


if __name__ == "__main__":
    main()
