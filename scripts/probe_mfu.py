#!/usr/bin/env python
"""MFU diagnostic probe: separate dispatch overhead from device compute.

Runs the ResNet-50 train step three ways:
  a) per-step dispatch (what bench.py does)
  b) k steps fused in ONE jit via lax.fori_loop (zero per-step dispatch)
  c) XLA cost_analysis FLOPs of the single step (sanity-check the MFU math)
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data import BenchmarkIterator
from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.train import Trainer

dev = jax.devices()[0]
on_tpu = dev.platform != "cpu"
batch = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 4))
img = int(os.environ.get("BENCH_IMG", 224 if on_tpu else 32))

zm = ResNet50(num_classes=1000, seed=0, input_shape=(img, img, 3))
model = zm.build()
if on_tpu:
    model.config.compute_dtype = "bfloat16"
model.init()

tr = Trainer(model)
step = tr._make_step()
it = BenchmarkIterator((img, img, 3), 1000, batch, 1)
ds = next(iter(it))
x = jax.device_put(np.asarray(ds.features))
y = jax.device_put(np.asarray(ds.labels))
rng = jax.random.PRNGKey(0)

params, opt_state, state = tr.params, tr.opt_state, tr.state

# --- c) cost analysis of the single step ---
lowered = step.lower(params, opt_state, state, x, y, rng)
compiled = lowered.compile()
try:
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    flops = ca.get("flops", float("nan"))
    print(f"cost_analysis flops/step: {flops:.3e}  "
          f"(per image: {flops/batch:.3e}; bench.py assumes 1.227e10/img @224)")
    for k in sorted(ca):
        if "bytes" in k and ca[k] > 1e9:
            print(f"  {k}: {ca[k]:.3e}")
except Exception as e:
    print("cost_analysis unavailable:", e)

# --- a) per-step dispatch ---
def run(k, params, opt_state, state):
    t0 = time.perf_counter()
    for _ in range(k):
        params, opt_state, state, loss = step(params, opt_state, state, x, y, rng)
    lf = float(loss)
    return time.perf_counter() - t0, params, opt_state, state

_, params, opt_state, state = run(3, params, opt_state, state)
t1, params, opt_state, state = run(5, params, opt_state, state)
t2, params, opt_state, state = run(20, params, opt_state, state)
per_step_dispatch = (t2 - t1) / 15
print(f"a) per-step dispatch: {per_step_dispatch*1e3:.2f} ms/step "
      f"({batch/per_step_dispatch:.1f} img/s)")

# --- b) fori_loop fused: k steps, one dispatch ---
tx, mdl = tr.tx, tr.model

@jax.jit
def multi(params, opt_state, state, k):
    def body(i, carry):
        p, o, s, _ = carry
        import optax

        def loss_fn(pp):
            loss, ns = mdl.score(pp, s, x, y, training=True, rng=rng)
            return loss, ns

        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return (p, o, ns, loss)

    return jax.lax.fori_loop(0, k, body, (params, opt_state, state, jnp.float32(0)))

r = multi(params, opt_state, state, 3)
_ = float(r[3])  # compile + warm
t0 = time.perf_counter()
r = multi(params, opt_state, state, 5)
_ = float(r[3])
t1 = time.perf_counter() - t0
t0 = time.perf_counter()
r = multi(params, opt_state, state, 20)
_ = float(r[3])
t2 = time.perf_counter() - t0
per_step_fused = (t2 - t1) / 15
print(f"b) fori_loop fused:  {per_step_fused*1e3:.2f} ms/step "
      f"({batch/per_step_fused:.1f} img/s)")
print(f"dispatch overhead per step: {(per_step_dispatch-per_step_fused)*1e3:.2f} ms")
