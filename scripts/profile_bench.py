#!/usr/bin/env python
"""Decompose ResNet-50 bench step time on the real chip.

Measures, each with the two-point slope method from bench.py:
  1. dispatch:   trivial jitted chained op   (pure tunnel/dispatch overhead)
  2. fwd:        forward pass only
  3. step_py:    full train step, python loop (what bench.py measures today)
  4. step_scan:  K train steps inside one jitted lax.scan (one dispatch)

Usage: python scripts/profile_bench.py [batch ...]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data import BenchmarkIterator
from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.train import Trainer

RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.09e9
PEAK = 197e12  # v5e bf16


def slope(fn, k1, k2):
    fn(3)  # warmup/compile
    t1 = fn(k1)
    t2 = fn(k2)
    return (t2 - t1) / (k2 - k1)


def main():
    batches = [int(b) for b in sys.argv[1:]] or [128, 256]
    dev = jax.devices()[0]
    print("device:", dev.device_kind)

    # 1. dispatch overhead: chained tiny op
    @jax.jit
    def tiny(x):
        return x + 1.0

    def run_tiny(k):
        x = jnp.zeros((8,))
        t0 = time.perf_counter()
        for _ in range(k):
            x = tiny(x)
        _ = float(x[0])
        return time.perf_counter() - t0

    dt = slope(run_tiny, 5, 40)
    print(f"dispatch per-call: {dt * 1e3:.2f} ms")

    for batch in batches:
        img = 224
        zm = ResNet50(num_classes=1000, seed=0, input_shape=(img, img, 3))
        model = zm.build()
        model.config.compute_dtype = "bfloat16"
        model.init()
        tr = Trainer(model)
        step = tr._make_step()
        it = BenchmarkIterator((img, img, 3), 1000, batch, 1)
        ds = next(iter(it))
        x = jax.device_put(np.asarray(ds.features))
        y = jax.device_put(np.asarray(ds.labels))
        rng = jax.random.PRNGKey(0)

        # forward only
        @jax.jit
        def fwd(params, state, x):
            ys, _ = model.forward(params, state, x, training=False)
            return ys[0]

        def run_fwd(k):
            t0 = time.perf_counter()
            o = None
            for _ in range(k):
                o = fwd(tr.params, tr.state, x)
            _ = float(o[0, 0])
            return time.perf_counter() - t0

        tf = slope(run_fwd, 3, 12)

        # full step, python loop
        params, opt_state, state = tr.params, tr.opt_state, tr.state

        def run_step(k):
            nonlocal params, opt_state, state
            t0 = time.perf_counter()
            for _ in range(k):
                params, opt_state, state, loss = step(params, opt_state, state, x, y, rng)
            _ = float(loss)
            return time.perf_counter() - t0

        tp = slope(run_step, 3, 12)

        # K steps in one scan
        model.init()  # fresh params (prior ones were donated by step)
        tr2 = Trainer(model)
        tx = tr2.tx

        def one(carry, _):
            p, o, s = carry
            def loss_fn(pp):
                l, ns = model.score(pp, s, x, y, training=True, rng=rng)
                return l, ns
            (l, ns), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            import optax
            up, o = tx.update(g, o, p)
            p = optax.apply_updates(p, up)
            return (p, o, ns), l

        def mk(k):
            def f(carry):
                return jax.lax.scan(one, carry, None, length=k)
            return jax.jit(f)

        f3, f12 = mk(3), mk(12)
        p0, o0, s0 = tr2.params, tr2.opt_state, tr2.state
        # warmup both
        r3 = f3((p0, o0, s0)); _ = float(r3[1][-1])
        r12 = f12((p0, o0, s0)); _ = float(r12[1][-1])
        t0 = time.perf_counter(); r3 = f3((p0, o0, s0)); _ = float(r3[1][-1])
        t3 = time.perf_counter() - t0
        t0 = time.perf_counter(); r12 = f12((p0, o0, s0)); _ = float(r12[1][-1])
        t12 = time.perf_counter() - t0
        ts = (t12 - t3) / 9

        for name, t in [("fwd", tf), ("step_py", tp), ("step_scan", ts)]:
            ips = batch / t
            mfu = ips * RESNET50_TRAIN_FLOPS_PER_IMAGE / PEAK if "step" in name else \
                  ips * 4.09e9 / PEAK
            print(f"b={batch} {name:10s}: {t * 1e3:7.2f} ms/step  {ips:8.1f} img/s  mfu={mfu:.3f}")


if __name__ == "__main__":
    main()
