#!/usr/bin/env python
"""Bench breadth — BASELINE.md configs 1-3 alongside ResNet-50 (r3 VERDICT
#10): LeNet-MNIST, GravesLSTM char-RNN, VGG16 step-time + MFU on one chip,
same two-point-slope methodology as bench.py. FLOPs per step come from XLA's
own cost model (``compiled.cost_analysis()``) so every model family is
counted consistently (fwd+bwd+optimizer, exactly what executes).

Usage (real chip):   python scripts/model_benches.py
CPU smoke test:      JAX_PLATFORMS=cpu MB_SMOKE=1 python scripts/model_benches.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# persistent XLA compile cache (same setting as bench.py) — effective only
# if jax hasn't initialized yet
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dl4j_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "5")

import numpy as np

PEAK_BF16 = {"TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5": 459e12,
             "TPU v5p": 459e12, "TPU v6 lite": 918e12}


def bench_model(name, build_fn, batch, in_shape, n_classes, *, seq=False,
                steps=20, bf16=True, on_tpu=True, token_vocab=None, spe=1,
                micro=1):
    """``spe`` > 1 measures the ``steps_per_execution`` megastep path
    (Trainer._make_multi_step): spe train steps scanned inside one compiled
    program, amortizing per-step dispatch — the honest number for small
    models whose single step is ~1-3 ms (dispatch-bound through the tunnel).
    ``micro`` > 1 measures the grad_accum path: micro microbatches of size
    ``batch`` per optimizer update (amortizes updater HBM traffic for
    100M+ param models). step_ms/flops are per (micro)batch step either
    way. spe and micro are mutually exclusive."""
    import jax

    from deeplearning4j_tpu.train import Trainer

    assert not (spe > 1 and micro > 1)
    model = build_fn()
    if on_tpu and bf16:
        model.config.compute_dtype = "bfloat16"
    model.init()
    tr = Trainer(model, grad_accum=micro)
    step = tr._make_step()
    rng = np.random.RandomState(0)
    x = rng.randn(batch, *in_shape).astype(np.float32)
    if token_vocab:  # (B, T) int token ids (BERT fine-tune shape)
        x = rng.randint(0, token_vocab, (batch, *in_shape)).astype(np.int32)
        y = np.eye(n_classes, dtype=np.float32)[rng.randint(0, n_classes, batch)]
    elif seq:  # (B, T, V) one-hot inputs + (B, T, V) targets (char-RNN)
        T, V = in_shape
        ids = rng.randint(0, V, (batch, T))
        x = np.eye(V, dtype=np.float32)[ids]
        y = np.eye(V, dtype=np.float32)[rng.randint(0, V, (batch, T))]
    else:
        y = np.eye(n_classes, dtype=np.float32)[rng.randint(0, n_classes, batch)]
    xd, yd = jax.device_put(x), jax.device_put(y)
    r = jax.random.PRNGKey(0)

    lowered = step.lower(tr.params, tr.opt_state, tr.state, xd, yd, r, None, None)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    flops = float((ca or {}).get("flops", 0.0))

    p, o, s = tr.params, tr.opt_state, tr.state
    p, o, s, loss = step(p, o, s, xd, yd, r, None, None)
    float(loss)  # force (also settles net_state structure for the megastep)

    if spe > 1:
        mstep = tr._make_multi_step()
        xs = jnp_stack_k(xd, spe)
        ys = jnp_stack_k(yd, spe)
        rs = jax.random.split(jax.random.PRNGKey(1), spe)
        p, o, s, losses = mstep(p, o, s, xs, ys, rs, None, None)  # compile+warm
        float(losses[-1])

        def run(k, p, o, s):
            t0 = time.perf_counter()
            for _ in range(k):
                p, o, s, losses = mstep(p, o, s, xs, ys, rs, None, None)
            float(losses[-1])
            return time.perf_counter() - t0, p, o, s
    elif micro > 1:
        astep = tr._make_accum_step()
        xs = jnp_stack_k(xd, micro)
        ys = jnp_stack_k(yd, micro)
        rs = jax.random.split(jax.random.PRNGKey(1), micro)
        p, o, s, loss = astep(p, o, s, xs, ys, rs, None, None)  # compile+warm
        float(loss)

        def run(k, p, o, s):
            t0 = time.perf_counter()
            for _ in range(k):
                p, o, s, loss = astep(p, o, s, xs, ys, rs, None, None)
            float(loss)
            return time.perf_counter() - t0, p, o, s
    else:
        def run(k, p, o, s):
            t0 = time.perf_counter()
            for _ in range(k):
                p, o, s, loss = step(p, o, s, xd, yd, r, None, None)
            float(loss)
            return time.perf_counter() - t0, p, o, s

    k1, k2 = max(steps // 4, 1), steps
    t1, p, o, s = run(k1, p, o, s)
    t2, p, o, s = run(k2, p, o, s)
    dt = (t2 - t1) / (k2 - k1) if t2 > t1 else t2 / k2
    dt /= spe * micro  # per (micro)batch train step either way
    dev = jax.devices()[0]
    peak = next((v for k, v in PEAK_BF16.items()
                 if str(dev.device_kind).startswith(k)), 197e12)
    row = {"model": name, "batch": batch, "step_ms": round(dt * 1e3, 2),
           "samples_per_sec": round(batch / dt, 1),
           "flops_per_step": flops,
           "mfu": round(flops / dt / peak, 4) if flops else None}
    if spe > 1:
        row["steps_per_execution"] = spe
    if micro > 1:
        row["grad_accum"] = micro
    return row


def jnp_stack_k(a, k):
    """(k, ...) broadcast-stack of one device array (D2D, no host trip)."""
    import jax.numpy as jnp

    return jnp.broadcast_to(a[None], (k,) + tuple(a.shape)).copy() \
        if hasattr(a, "shape") else a


def bench_transformer(*, num_layers=12, d_model=1536, batch=8, seq=1024,
                      vocab=32000, flash=True, steps=15, smoke=False,
                      micro=1, remat=False, pos="learned", window=None):
    """The matmul-dominated envelope case (PERF.md: 440M CausalLM + flash
    kernel measured at MFU 0.45 where exact-BN ResNet-50 caps ~0.36-0.40).
    Sparse integer labels — no (B, T, V) one-hot. ``micro=N`` measures the
    grad_accum path: N microbatches of size ``batch`` per optimizer update
    (one compiled program) — amortizes the AdamW HBM pass, the dominant
    non-matmul cost at 500M+ params. step_ms/tokens are per MICROBATCH so
    rows stay comparable."""
    import jax

    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.train import Trainer

    if smoke:
        num_layers, d_model, batch, seq, vocab, steps = 2, 64, 2, 64, 128, 2
    zm = CausalLM(seed=0, input_shape=(seq,), num_layers=num_layers,
                  d_model=d_model, num_heads=max(d_model // 64, 1),
                  vocab=vocab, flash=flash, remat=remat, pos=pos,
                  window=window)
    model = zm.build()
    if not smoke:
        model.config.compute_dtype = "bfloat16"
    model.init()
    tr = Trainer(model, grad_accum=micro)
    rng = np.random.RandomState(0)
    x = jax.device_put(rng.randint(0, vocab, (micro * batch, seq)).astype(np.int32))
    y = jax.device_put(rng.randint(0, vocab, (micro * batch, seq)).astype(np.int32))
    r = jax.random.PRNGKey(0)
    if micro > 1:
        import jax.numpy as jnp

        step = tr._make_accum_step()
        xs = x.reshape(micro, batch, seq)
        ys = y.reshape(micro, batch, seq)
        rs = jax.random.split(r, micro)
        args = (xs, ys, rs, None, None)
    else:
        step = tr._make_step()
        args = (x, y, r, None, None)
    compiled = step.lower(tr.params, tr.opt_state, tr.state, *args).compile()
    flops = float((compiled.cost_analysis() or {}).get("flops", 0.0)) / micro
    p, o, s = tr.params, tr.opt_state, tr.state
    p, o, s, loss = step(p, o, s, *args)
    float(loss)

    def run(k, p, o, s):
        t0 = time.perf_counter()
        for _ in range(k):
            p, o, s, loss = step(p, o, s, *args)
        float(loss)
        return (time.perf_counter() - t0) / micro, p, o, s

    k1, k2 = max(steps // 4, 1), steps
    t1, p, o, s = run(k1, p, o, s)
    t2, p, o, s = run(k2, p, o, s)
    dt = (t2 - t1) / (k2 - k1) if t2 > t1 else t2 / k2
    dev = jax.devices()[0]
    peak = next((v for k, v in PEAK_BF16.items()
                 if str(dev.device_kind).startswith(k)), 197e12)
    n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(tr.params))
    row = {"model": f"causal_lm_{n_params/1e6:.0f}M_{'flash' if flash else 'dense'}",
           "batch": batch, "seq": seq, "step_ms": round(dt * 1e3, 2),
           "tokens_per_sec": round(batch * seq / dt, 1),
           "flops_per_step": flops,
           "mfu": round(flops / dt / peak, 4) if flops else None}
    if micro > 1:
        row["grad_accum"] = micro
    return row


def main():
    import jax

    smoke = bool(os.environ.get("MB_SMOKE"))
    on_tpu = jax.devices()[0].platform == "tpu"
    from deeplearning4j_tpu.models import (BertBase, LeNet, ResNet50, VGG16,
                                           GravesLSTMCharRNN)

    img = 224 if (on_tpu and not smoke) else 32
    jobs = [
        ("lenet_mnist",
         lambda: LeNet(num_classes=10, seed=0, input_shape=(28, 28, 1)).build(),
         dict(batch=8 if smoke else 1024, in_shape=(28, 28, 1), n_classes=10)),
        ("graves_lstm_char_rnn",
         lambda: GravesLSTMCharRNN(seed=0, tbptt=0).build(),
         dict(batch=4 if smoke else 128, in_shape=(64, 98), n_classes=98,
              seq=True)),
        ("vgg16",
         lambda: VGG16(num_classes=1000, seed=0,
                       input_shape=(img, img, 3)).build(),
         dict(batch=2 if smoke else 64, in_shape=(img, img, 3),
              n_classes=1000)),
        ("resnet50",
         lambda: ResNet50(num_classes=1000, seed=0,
                          input_shape=(img, img, 3)).build(),
         dict(batch=2 if smoke else 128, in_shape=(img, img, 3),
              n_classes=1000)),
        # BASELINE config 5 (stretch): BERT-base fine-tune shape — the
        # architecture the Keras/HF import path targets (models/transformer.py
        # BertBase; keras_import golden tests cover the weight path).
        ("bert_base_t128",
         lambda: BertBase(small=smoke, num_classes=2, seed=0,
                          input_shape=(16 if smoke else 128,),
                          flash=False).build(),
         dict(batch=2 if smoke else 64, in_shape=(16 if smoke else 128,),
              n_classes=2, token_vocab=1000 if smoke else 30522)),
    ]
    steps = 3 if smoke else 20
    for name, build, kw in jobs:
        try:
            row = bench_model(name, build, steps=steps, bf16=on_tpu,
                              on_tpu=on_tpu, **kw)
        except Exception as e:
            row = {"model": name, "error": f"{type(e).__name__}: {str(e)[:160]}"}
        print(json.dumps(row), flush=True)
    try:
        row = bench_transformer(smoke=smoke, flash=on_tpu)
    except Exception as e:
        row = {"model": "causal_lm", "error": f"{type(e).__name__}: {str(e)[:160]}"}
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
