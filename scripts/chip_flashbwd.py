#!/usr/bin/env python
"""On-chip validation + A/B of the Mosaic flash backward vs the XLA scan
backward. Small sizes, no external timeout (sized to finish)."""
import sys
import time

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

from chiputil import smoke_or_probe

SMOKE = smoke_or_probe()

import jax
import jax.numpy as jnp
import numpy as np

import deeplearning4j_tpu.ops.flash_attention as fa

def timed_grads(backend, B, T, H, D, causal=True, iters=8, dtype=np.float32):
    # Fresh seeded RNG per call: both backends must see IDENTICAL inputs or
    # the correctness comparison below is meaningless (a shared module-level
    # RandomState advanced between calls once made pallas-vs-xla compare
    # gradients at two different random points — rel err ~1.1, a harness
    # bug, not a kernel bug).
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), dtype) for _ in range(3))

    @jax.jit
    def g(q, k, v, carry):
        # carry chains iteration i to i-1 (value-neutral: *0) so ONE host
        # fetch after the loop waits for the whole chain — no per-iteration
        # RTT, no reliance on block_until_ready (unreliable through the
        # tunnel: measured flat 0.04ms for workloads differing 100x in
        # FLOPs).
        def loss(q, k, v):
            return jnp.sum(fa.flash_attention(q, k, v, causal=causal,
                                              backward=backend) ** 2)
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(
            q + (carry * 0).astype(q.dtype), k, v)
        sync = (jnp.sum(dq.astype(jnp.float32))
                + jnp.sum(dk.astype(jnp.float32))
                + jnp.sum(dv.astype(jnp.float32)))
        return (dq, dk, dv), sync

    carry = jnp.float32(0)
    r, carry = g(q, k, v, carry)  # compile + warm
    float(carry)
    t0 = time.perf_counter()
    for _ in range(iters):
        r, carry = g(q, k, v, carry)
    float(carry)  # single sync point for the chain
    return r, (time.perf_counter() - t0) / iters * 1e3

# --smoke: CPU shakeout at tiny sizes (the Pallas kernel runs
# interpreted on CPU; minutes per extra block) — same code paths
T1 = 128 if SMOKE else 1024
IT = 1 if SMOKE else 8

# 1. correctness: pallas vs xla on-chip (f32)
where = "CPU interpret (smoke)" if SMOKE else "TPU"
try:
    gp, tp_ms = timed_grads("pallas", 2, T1, 4, 64, iters=IT)
    print(f"pallas bwd compiles on {where}: OK  ({tp_ms:.2f} ms @T={T1})")
except Exception as e:
    print(f"pallas bwd FAILED on {where}: {type(e).__name__}: {str(e)[:400]}")
    raise SystemExit(1)
gx, tx_ms = timed_grads("xla", 2, T1, 4, 64, iters=IT)
for a, b, n in zip(gp, gx, "qkv"):
    err = float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
    print(f"d{n} rel-max-err pallas vs xla: {err:.2e}")
    assert err < 2e-3, (n, err)
print(f"T={T1} f32: pallas {tp_ms:.2f} ms vs xla {tx_ms:.2f} ms")

# 1b. ragged-lengths Mosaic lowering: the lens scalar load + dynamic
# interior predicates must agree with the dense key-masked oracle on chip
# (interpret-mode equivalence already proven in tests/test_flash_attention.py)
def ragged_check():
    rng = np.random.RandomState(3)
    B, T, H, D = 3, (128 if SMOKE else 384), 4, 64
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))
    lengths = jnp.asarray([T, T // 3, (3 * T) // 4])
    key_mask = (jnp.arange(T)[None, :] < lengths[:, None])[:, None, None]
    mask = key_mask & jnp.tril(jnp.ones((T, T), bool))[None, None]

    import deeplearning4j_tpu.nn.layers.attention as attn

    for backend in ("xla", "pallas"):
        def loss_f(q, k, v):
            o = fa.flash_attention(q, k, v, causal=True, lengths=lengths,
                                   backward=backend)
            return jnp.sum(o ** 2)

        def loss_d(q, k, v):
            return jnp.sum(attn.dot_product_attention(q, k, v, mask=mask) ** 2)

        gf = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(loss_d, argnums=(0, 1, 2)))(q, k, v)
        for n, a, b in zip("qkv", gf, gd):
            err = float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
            print(f"ragged {backend} d{n}: rel-max-err {err:.2e}")
            assert err < 2e-3, (backend, n, err)

        # exact key_mask path (arbitrary mask: left pad + holes).
        # Rows with NO visible key under mask&causal are degenerate (the
        # dense oracle softmaxes a -1e30 row to uniform junk, the kernel
        # emits zeros — neither is "correct"), so the comparison loss
        # weights them out; everything defined must still match.
        km = np.ones((B, T), bool)
        km[1, :T // 3] = False       # left-padded
        km[2, T // 4:T // 2] = False  # mid-sequence hole
        kmj = jnp.asarray(km)
        maskx = kmj[:, None, None, :] & jnp.tril(jnp.ones((T, T), bool))[None, None]
        valid_row = jnp.any(maskx, axis=-1).astype(jnp.float32)  # (B,1,T)
        vw = valid_row[..., None].swapaxes(1, 2)                 # (B,T,1,1)

        def loss_fm(q, k, v):
            o = fa.flash_attention(q, k, v, causal=True, key_mask=kmj,
                                   backward=backend)
            return jnp.sum((o * vw) ** 2)

        def loss_dm(q, k, v):
            o = attn.dot_product_attention(q, k, v, mask=maskx)
            return jnp.sum((o * vw) ** 2)

        gf = jax.jit(jax.grad(loss_fm, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(loss_dm, argnums=(0, 1, 2)))(q, k, v)
        for n, a, b in zip("qkv", gf, gd):
            err = float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
            print(f"keymask {backend} d{n}: rel-max-err {err:.2e}")
            assert err < 2e-3, (backend, n, err)
        # sliding window band
        W = min(96, T // 2)
        d = jnp.arange(T)[:, None] - jnp.arange(T)[None, :]
        bandm = ((d >= 0) & (d < W))[None, None]

        def loss_fw(q, k, v):
            o = fa.flash_attention(q, k, v, causal=True, window=W,
                                   backward=backend)
            return jnp.sum(o ** 2)

        def loss_dw(q, k, v):
            return jnp.sum(attn.dot_product_attention(q, k, v, mask=bandm) ** 2)

        gf = jax.jit(jax.grad(loss_fw, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(loss_dw, argnums=(0, 1, 2)))(q, k, v)
        for n, a, b in zip("qkv", gf, gd):
            err = float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
            print(f"window {backend} d{n}: rel-max-err {err:.2e}")
            assert err < 2e-3, (backend, n, err)
    print("ragged lengths + exact key_mask + sliding window: Mosaic fwd+bwd "
          "match dense oracle on chip")

ragged_check()

# 2. long-context bf16 timing (the regime the kernel targets)
for T in (() if SMOKE else (2048, 4096)):
    _, tp_ms = timed_grads("pallas", 2, T, 8, 64, dtype=jnp.bfloat16, iters=5)
    _, tx_ms = timed_grads("xla", 2, T, 8, 64, dtype=jnp.bfloat16, iters=5)
    print(f"T={T} bf16 B=2 H=8: pallas {tp_ms:.2f} ms vs xla {tx_ms:.2f} ms "
          f"({tx_ms / tp_ms:.2f}x)")
print("DONE")
