#!/usr/bin/env python
"""CI smoke serve: boot a ModelServer on a small CausalLM, fire mixed
predict/generate traffic at it concurrently, and assert the ISSUE-4/5
acceptance surface — every request answered (zero drops below capacity),
greedy /generate matches whole-batch ``nn.generation.generate`` on both the
buffered and the SSE-streamed path, the executable set stays bounded, a
long-prompt burst that OVERCOMMITS the paged-KV pool queues and completes
(with a truly-impossible request shed as a typed ``CapacityError``), and
the Prometheus scrape exposes the serving histograms/counters plus the
paged-KV block gauges — so a regression in the serving path fails CI before
it reaches a real deployment.

ISSUE-6 addition: the server is then booted TWICE against one persistent
AOT store directory — the second boot must serve identical results with
ZERO decode-path XLA compiles (``serve_compile_misses_total`` stays 0) and
``serve_aot_hits_total > 0`` in its scrape.

Artifacts land in $CI_ARTIFACTS_DIR (default: ./ci-artifacts/):
smoke_serve_metrics.prom (the final /metrics scrape of the main server),
smoke_serve_warmboot.prom (the warm second boot's scrape), aot_store/
(the store both boots shared).
"""

import concurrent.futures as cf
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

PREDICTS = 12
GENERATES = 6

REQUIRED_METRICS = (
    "serve_queue_depth", "serve_queue_seconds_bucket",
    "serve_device_seconds_bucket", "serve_batch_occupancy_bucket",
    "serve_batches_total", "serve_requests_total",
    "serve_compile_misses_total", "serve_model_generation",
    "serve_gen_admitted_total", "serve_gen_completed_total",
    "serve_gen_tokens_total", "http_request_seconds_bucket",
    # paged-KV + chunked-prefill surface (ISSUE 5)
    "serve_kv_blocks_total", "serve_kv_blocks_used",
    "serve_kv_block_utilization", "serve_kv_live_bytes",
    "serve_prefill_chunks_total", "serve_lease_total",
)


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _sse_generate(port, body):
    """POST /generate on the default (streaming) path; return the token
    list from the per-token SSE events, cross-checked against the final
    ``done`` event."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers["Content-Type"] == "text/event-stream", \
            "/generate did not stream by default"
        for line in r:
            if line.startswith(b"data: "):
                events.append(json.loads(line[len(b"data: "):]))
    assert events and events[-1].get("done"), events[-1:]
    toks = [e["token"] for e in events[:-1]]
    assert events[-1]["tokens"] == toks, "SSE final event disagrees"
    return toks


def _overcommit_burst(model):
    """Long-prompt burst against a deliberately tiny block pool: total
    demand (6 requests x 10 tokens) overcommits the 4-usable-block pool
    (16 KV tokens), so requests queue on block availability and ALL must
    still complete bit-exactly; a request that can NEVER fit is shed as a
    typed CapacityError at submit."""
    import concurrent.futures as cf

    from deeplearning4j_tpu.nn.generation import generate
    from deeplearning4j_tpu.serve import CapacityError, ContinuousBatcher

    cb = ContinuousBatcher(model, slots=4, capacity=32, block_size=4,
                           kv_blocks=5, prefill_chunk=8, queue_limit=16,
                           seed=0)
    try:
        rng = np.random.RandomState(42)
        prompts = [rng.randint(0, 50, (6,)).astype(np.int32)
                   for _ in range(6)]
        with cf.ThreadPoolExecutor(6) as ex:
            outs = list(ex.map(
                lambda p: cb.generate(p, 4, temperature=0.0), prompts))
        for p, o in zip(prompts, outs):
            want = generate(model, p[None], 4, temperature=0.0)[0]
            assert o.tolist() == want.tolist(), "overcommit corrupted decode"
        stats = cb.kv_block_stats()
        assert stats["blocks_used"] == 0, stats  # everything retired
        try:
            cb.submit(np.zeros(12, np.int32), 8)  # 20 tokens > 16-token pool
            raise AssertionError("impossible request was admitted")
        except CapacityError:
            pass
        return stats["blocks_total"]
    finally:
        cb.shutdown()


def _prom_total(scrape, name):
    """Sum every series of one metric in a Prometheus text scrape."""
    total = 0.0
    for line in scrape.splitlines():
        if line.startswith(name) and len(line) > len(name) \
                and line[len(name)] in "{ ":
            total += float(line.rsplit(" ", 1)[1])
    return total


def _aot_warm_boot(out_dir):
    """Boot a server twice against ONE persistent AOT store. Boot 1 traces
    live and persists every executable; boot 2 must load them all back —
    identical greedy output, serve_aot_hits_total > 0, and ZERO XLA
    compiles on the compile-miss counter (the ISSUE-6 acceptance gate)."""
    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.serve import ModelServer

    store_dir = os.path.join(out_dir, "aot_store")

    def boot():
        model = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                         num_heads=4, vocab=50).build()
        model.init()
        srv = ModelServer(model, port=0, input_dtype=np.int32,
                          batch_buckets=(1, 2, 4, 8), gen_slots=2,
                          gen_capacity=16,
                          aot_store=AotStore(store_dir)).start()
        try:
            pred = _post(srv.port, "/predict",
                         {"ndarray": [[1] * 8, [2] * 8]})["output"]
            toks = _post(srv.port, "/generate?stream=false",
                         {"prompt": [1, 2, 3], "max_new_tokens": 3,
                          "temperature": 0.0})["tokens"]
            models = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/models", timeout=10).read())
            scrape = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=10).read().decode()
        finally:
            srv.stop()
        return pred, toks, models, scrape

    pred1, toks1, _, _ = boot()          # cold: trace + persist
    pred2, toks2, models, scrape = boot()  # warm: disk only
    assert toks1 == toks2 and pred1 == pred2, \
        "warm boot changed serving output"
    assert models.get("aot_store", {}).get("entries", 0) > 0, models
    hits = _prom_total(scrape, "serve_aot_hits_total")
    compiles = _prom_total(scrape, "serve_compile_misses_total")
    fallbacks = _prom_total(scrape, "serve_aot_fallback_total")
    assert hits > 0, "second boot took no AOT store hits"
    assert compiles == 0, \
        f"second boot traced ({compiles} compile misses) despite warm store"
    assert fallbacks == 0, f"warm store fell back {fallbacks} time(s)"
    with open(os.path.join(out_dir, "smoke_serve_warmboot.prom"), "w") as f:
        f.write(scrape)
    return int(hits)


def main() -> int:
    out_dir = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(out_dir, exist_ok=True)

    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.nn.generation import generate
    from deeplearning4j_tpu.serve import ModelServer

    model = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                     num_heads=4, vocab=50).build()
    model.init()
    srv = ModelServer(model, port=0, input_dtype=np.int32,
                      batch_buckets=(1, 2, 4, 8), gen_slots=2,
                      gen_capacity=16).start()
    try:
        rng = np.random.RandomState(0)
        jobs = []
        for _ in range(PREDICTS):
            ids = rng.randint(0, 50, (int(rng.randint(1, 5)), 8)).tolist()
            jobs.append(("/predict", {"ndarray": ids}))
        for _ in range(GENERATES):
            prompt = rng.randint(0, 50, (int(rng.randint(3, 9)),)).tolist()
            jobs.append(("/generate?stream=false",
                         {"prompt": prompt, "max_new_tokens": 4,
                          "temperature": 0.0}))
        rng.shuffle(jobs)
        with cf.ThreadPoolExecutor(8) as ex:
            replies = list(ex.map(lambda j: (j, _post(srv.port, *j)), jobs))
        assert len(replies) == PREDICTS + GENERATES, "dropped responses"

        # greedy /generate is bit-identical to whole-batch generation
        for (path, body), reply in replies:
            if path == "/predict":
                want = np.asarray(model.output(
                    np.asarray(body["ndarray"], np.int32)))
                np.testing.assert_allclose(np.asarray(reply["output"]), want,
                                           rtol=1e-4, atol=1e-5)
            else:
                want = generate(model, np.asarray([body["prompt"]], np.int32),
                                4, temperature=0.0)[0]
                assert reply["tokens"] == want.tolist(), \
                    (path, body, reply, want)

        # default /generate streams SSE, token-identical to the buffered path
        sse_prompt = rng.randint(0, 50, (7,)).tolist()
        sse_body = {"prompt": sse_prompt, "max_new_tokens": 4,
                    "temperature": 0.0}
        sse_toks = _sse_generate(srv.port, sse_body)
        assert sse_toks == _post(srv.port, "/generate?stream=false",
                                 sse_body)["tokens"], "SSE != buffered"

        # bounded executables: engine <= |batch buckets|, batcher <=
        # |prompt buckets| + one decode step
        n_eng = len(srv.engine.compile_signatures)
        assert n_eng <= 4, srv.engine.compile_signatures
        bat = srv.batcher()
        n_gen = len(bat.compile_signatures)
        assert n_gen <= len(bat.prompt_buckets) + 1, bat.compile_signatures

        # long-prompt burst overcommitting a tiny pool (separate batcher so
        # the server's own pool sizing is untouched)
        pool_blocks = _overcommit_burst(model)

        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/health", timeout=10).read())
        assert health["status"] == "ok"
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read().decode()
        for needle in REQUIRED_METRICS:
            assert needle in scrape, f"missing {needle} in /metrics"

        prom_path = os.path.join(out_dir, "smoke_serve_metrics.prom")
        with open(prom_path, "w") as f:
            f.write(scrape)
        print(f"smoke_serve: {PREDICTS} predicts + {GENERATES} generates "
              f"+ SSE + overcommit burst ({pool_blocks}-block pool), "
              f"{n_eng} engine compile(s), {n_gen} generate compile(s), "
              f"generation {health['generation']} -> {prom_path}")
    finally:
        srv.stop()

    # cold-start acceptance: second boot against a warm AOT store serves
    # with zero XLA compiles
    aot_hits = _aot_warm_boot(out_dir)
    print(f"smoke_serve: warm second boot served from the AOT store "
          f"({aot_hits} executable loads, 0 compiles)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
