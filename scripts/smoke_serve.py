#!/usr/bin/env python
"""CI smoke serve: boot a ModelServer on a small CausalLM, fire mixed
predict/generate traffic at it concurrently, and assert the ISSUE-4
acceptance surface — every request answered (zero drops below capacity),
greedy /generate matches whole-batch ``nn.generation.generate``, the
executable set stays bounded, and the Prometheus scrape exposes the serving
histograms/counters — so a regression in the serving path fails CI before
it reaches a real deployment.

Artifacts land in $CI_ARTIFACTS_DIR (default: ./ci-artifacts/):
smoke_serve_metrics.prom (the final /metrics scrape).
"""

import concurrent.futures as cf
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

PREDICTS = 12
GENERATES = 6

REQUIRED_METRICS = (
    "serve_queue_depth", "serve_queue_seconds_bucket",
    "serve_device_seconds_bucket", "serve_batch_occupancy_bucket",
    "serve_batches_total", "serve_requests_total",
    "serve_compile_misses_total", "serve_model_generation",
    "serve_gen_admitted_total", "serve_gen_completed_total",
    "serve_gen_tokens_total", "http_request_seconds_bucket",
)


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def main() -> int:
    out_dir = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(out_dir, exist_ok=True)

    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.nn.generation import generate
    from deeplearning4j_tpu.serve import ModelServer

    model = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                     num_heads=4, vocab=50).build()
    model.init()
    srv = ModelServer(model, port=0, input_dtype=np.int32,
                      batch_buckets=(1, 2, 4, 8), gen_slots=2,
                      gen_capacity=16).start()
    try:
        rng = np.random.RandomState(0)
        jobs = []
        for _ in range(PREDICTS):
            ids = rng.randint(0, 50, (int(rng.randint(1, 5)), 8)).tolist()
            jobs.append(("/predict", {"ndarray": ids}))
        for _ in range(GENERATES):
            prompt = rng.randint(0, 50, (int(rng.randint(3, 9)),)).tolist()
            jobs.append(("/generate", {"prompt": prompt, "max_new_tokens": 4,
                                       "temperature": 0.0}))
        rng.shuffle(jobs)
        with cf.ThreadPoolExecutor(8) as ex:
            replies = list(ex.map(lambda j: (j, _post(srv.port, *j)), jobs))
        assert len(replies) == PREDICTS + GENERATES, "dropped responses"

        # greedy /generate is bit-identical to whole-batch generation
        for (path, body), reply in replies:
            if path == "/predict":
                want = np.asarray(model.output(
                    np.asarray(body["ndarray"], np.int32)))
                np.testing.assert_allclose(np.asarray(reply["output"]), want,
                                           rtol=1e-4, atol=1e-5)
            else:
                want = generate(model, np.asarray([body["prompt"]], np.int32),
                                4, temperature=0.0)[0]
                assert reply["tokens"] == want.tolist(), \
                    (path, body, reply, want)

        # bounded executables: engine <= |batch buckets|, batcher <=
        # |prompt buckets| + one decode step
        n_eng = len(srv.engine.compile_signatures)
        assert n_eng <= 4, srv.engine.compile_signatures
        bat = srv.batcher()
        n_gen = len(bat.compile_signatures)
        assert n_gen <= len(bat.prompt_buckets) + 1, bat.compile_signatures

        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/health", timeout=10).read())
        assert health["status"] == "ok"
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read().decode()
        for needle in REQUIRED_METRICS:
            assert needle in scrape, f"missing {needle} in /metrics"

        prom_path = os.path.join(out_dir, "smoke_serve_metrics.prom")
        with open(prom_path, "w") as f:
            f.write(scrape)
        print(f"smoke_serve: {PREDICTS} predicts + {GENERATES} generates, "
              f"{n_eng} engine compile(s), {n_gen} generate compile(s), "
              f"generation {health['generation']} -> {prom_path}")
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
