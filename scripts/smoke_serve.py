#!/usr/bin/env python
"""CI smoke serve: boot a ModelServer on a small CausalLM, fire mixed
predict/generate traffic at it concurrently, and assert the ISSUE-4/5
acceptance surface — every request answered (zero drops below capacity),
greedy /generate matches whole-batch ``nn.generation.generate`` on both the
buffered and the SSE-streamed path, the executable set stays bounded, a
long-prompt burst that OVERCOMMITS the paged-KV pool queues and completes
(with a truly-impossible request shed as a typed ``CapacityError``), and
the Prometheus scrape exposes the serving histograms/counters plus the
paged-KV block gauges — so a regression in the serving path fails CI before
it reaches a real deployment.

ISSUE-6 addition: the server is then booted TWICE against one persistent
AOT store directory — the second boot must serve identical results with
ZERO decode-path XLA compiles (``serve_compile_misses_total`` stays 0) and
``serve_aot_hits_total > 0`` in its scrape.

ISSUE-16 addition: the full prebuild farm loop — the jaxlint enumeration
manifest (compile-surface bounds x the committed scripts/serve_config.json)
is compiled into a fresh store by ``aot prebuild --from-surface``, a STRICT
replica boots from it and serves mixed bucket traffic with ZERO compile
misses/fallbacks, and a deliberately incomplete store fails the next strict
boot with a typed ``AotTraceError`` — never a trace.

Artifacts land in $CI_ARTIFACTS_DIR (default: ./ci-artifacts/):
smoke_serve_metrics.prom (the final /metrics scrape of the main server),
smoke_serve_warmboot.prom (the warm second boot's scrape), aot_store/
(the store both boots shared), prebuild_manifest.json + prebuild_coverage.json
(the enumeration manifest and the store's stamped coverage record),
smoke_serve_strict.prom (the strict replica's scrape — carries the
``profile_*`` and ``serve_padding_waste_ratio`` families), and
cost_profile.json (the continuous profiler's measured CostProfile,
also persisted into the prebuilt store for tuner-boot calibration).
"""

import concurrent.futures as cf
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

PREDICTS = 12
GENERATES = 6

REQUIRED_METRICS = (
    "serve_queue_depth", "serve_queue_seconds_bucket",
    "serve_device_seconds_bucket", "serve_batch_occupancy_bucket",
    "serve_batches_total", "serve_requests_total",
    "serve_compile_misses_total", "serve_model_generation",
    "serve_gen_admitted_total", "serve_gen_completed_total",
    "serve_gen_tokens_total", "http_request_seconds_bucket",
    # paged-KV + chunked-prefill surface (ISSUE 5)
    "serve_kv_blocks_total", "serve_kv_blocks_used",
    "serve_kv_block_utilization", "serve_kv_live_bytes",
    "serve_prefill_chunks_total", "serve_lease_total",
    # prefix-cache / CoW / fork surface (ISSUE 20)
    "serve_prefix_cache_hits_total", "serve_prefix_cache_misses_total",
    "serve_prefill_tokens_saved_total", "serve_prefix_blocks_shared",
    "serve_kv_cow_copies_total", "serve_gen_forks_total",
)


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _sse_generate(port, body):
    """POST /generate on the default (streaming) path; return the token
    list from the per-token SSE events, cross-checked against the final
    ``done`` event."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers["Content-Type"] == "text/event-stream", \
            "/generate did not stream by default"
        for line in r:
            if line.startswith(b"data: "):
                events.append(json.loads(line[len(b"data: "):]))
    assert events and events[-1].get("done"), events[-1:]
    toks = [e["token"] for e in events[:-1]]
    assert events[-1]["tokens"] == toks, "SSE final event disagrees"
    return toks


def _overcommit_burst(model):
    """Long-prompt burst against a deliberately tiny block pool: total
    demand (6 requests x 10 tokens) overcommits the 4-usable-block pool
    (16 KV tokens), so requests queue on block availability and ALL must
    still complete bit-exactly; a request that can NEVER fit is shed as a
    typed CapacityError at submit."""
    import concurrent.futures as cf

    from deeplearning4j_tpu.nn.generation import generate
    from deeplearning4j_tpu.serve import CapacityError, ContinuousBatcher

    cb = ContinuousBatcher(model, slots=4, capacity=32, block_size=4,
                           kv_blocks=5, prefill_chunk=8, queue_limit=16,
                           seed=0)
    try:
        rng = np.random.RandomState(42)
        prompts = [rng.randint(0, 50, (6,)).astype(np.int32)
                   for _ in range(6)]
        with cf.ThreadPoolExecutor(6) as ex:
            outs = list(ex.map(
                lambda p: cb.generate(p, 4, temperature=0.0), prompts))
        for p, o in zip(prompts, outs):
            want = generate(model, p[None], 4, temperature=0.0)[0]
            assert o.tolist() == want.tolist(), "overcommit corrupted decode"
        cb.flush_prefix_cache()  # cache-retained blocks count as used
        stats = cb.kv_block_stats()
        assert stats["blocks_used"] == 0, stats  # everything retired
        try:
            cb.submit(np.zeros(12, np.int32), 8)  # 20 tokens > 16-token pool
            raise AssertionError("impossible request was admitted")
        except CapacityError:
            pass
        return stats["blocks_total"]
    finally:
        cb.shutdown()


def _prefix_cache_scenario(model):
    """ISSUE-20 acceptance: N concurrent requests share one system prompt.
    A primer request populates the prefix cache; the burst must take cache
    hits (counters move), decode bit-identically to whole-batch dense
    ``generate``, compile NOTHING new (adoption changes block-table
    contents, never shapes), and after drain + flush every refcount is
    back to zero (``blocks_used == 0``, nothing cached or shared)."""
    import concurrent.futures as cf

    from deeplearning4j_tpu.nn.generation import generate
    from deeplearning4j_tpu.serve import ContinuousBatcher

    cb = ContinuousBatcher(model, slots=2, capacity=16, block_size=4,
                           kv_blocks=16, prefill_chunk=4,
                           prompt_buckets=(4, 8, 12, 16), queue_limit=16,
                           seed=0)
    try:
        rng = np.random.RandomState(11)
        sys_prompt = rng.randint(0, 50, (8,)).astype(np.int32)  # 2 blocks
        prompts = [np.concatenate(
            [sys_prompt, rng.randint(0, 50, (3,)).astype(np.int32)])
            for _ in range(6)]
        # primer: warms every executable and inserts the shared blocks
        cb.generate(np.concatenate(
            [sys_prompt, rng.randint(0, 50, (3,)).astype(np.int32)]),
            4, temperature=0.0)
        sigs_before = set(cb.compile_signatures)
        with cf.ThreadPoolExecutor(6) as ex:
            outs = list(ex.map(
                lambda p: cb.generate(p, 4, temperature=0.0), prompts))
        for p, o in zip(prompts, outs):
            want = generate(model, p[None], 4, temperature=0.0)[0]
            assert o.tolist() == want.tolist(), \
                "cached decode diverged from dense"
        assert set(cb.compile_signatures) == sigs_before, \
            "prefix-cache burst compiled a new executable"
        stats = cb.kv_block_stats()
        px = stats["prefix_cache"]
        assert px["hits"] >= len(prompts), px  # every burst request hit
        saved = cb.metrics.counter("serve_prefill_tokens_saved_total").value
        assert saved >= len(prompts) * 8, saved  # 2 whole blocks each
        assert stats["blocks_cached"] > 0, stats  # cache is live pre-flush
        cb.flush_prefix_cache()
        stats = cb.kv_block_stats()
        assert stats["blocks_used"] == 0, stats  # every refcount back to 0
        assert stats["blocks_cached"] == 0 and stats["blocks_shared"] == 0, \
            stats
        return int(px["hits"]), int(saved)
    finally:
        cb.shutdown()


def _prom_total(scrape, name):
    """Sum every series of one metric in a Prometheus text scrape."""
    total = 0.0
    for line in scrape.splitlines():
        if line.startswith(name) and len(line) > len(name) \
                and line[len(name)] in "{ ":
            total += float(line.rsplit(" ", 1)[1])
    return total


def _aot_warm_boot(out_dir):
    """Boot a server twice against ONE persistent AOT store. Boot 1 traces
    live and persists every executable; boot 2 must load them all back —
    identical greedy output, serve_aot_hits_total > 0, and ZERO XLA
    compiles on the compile-miss counter (the ISSUE-6 acceptance gate)."""
    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.serve import ModelServer

    store_dir = os.path.join(out_dir, "aot_store")

    def boot():
        model = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                         num_heads=4, vocab=50).build()
        model.init()
        srv = ModelServer(model, port=0, input_dtype=np.int32,
                          batch_buckets=(1, 2, 4, 8), gen_slots=2,
                          gen_capacity=16,
                          aot_store=AotStore(store_dir)).start()
        try:
            pred = _post(srv.port, "/predict",
                         {"ndarray": [[1] * 8, [2] * 8]})["output"]
            toks = _post(srv.port, "/generate?stream=false",
                         {"prompt": [1, 2, 3], "max_new_tokens": 3,
                          "temperature": 0.0})["tokens"]
            models = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/models", timeout=10).read())
            scrape = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=10).read().decode()
        finally:
            srv.stop()
        return pred, toks, models, scrape

    pred1, toks1, _, _ = boot()          # cold: trace + persist
    pred2, toks2, models, scrape = boot()  # warm: disk only
    assert toks1 == toks2 and pred1 == pred2, \
        "warm boot changed serving output"
    assert models.get("aot_store", {}).get("entries", 0) > 0, models
    hits = _prom_total(scrape, "serve_aot_hits_total")
    compiles = _prom_total(scrape, "serve_compile_misses_total")
    fallbacks = _prom_total(scrape, "serve_aot_fallback_total")
    assert hits > 0, "second boot took no AOT store hits"
    assert compiles == 0, \
        f"second boot traced ({compiles} compile misses) despite warm store"
    assert fallbacks == 0, f"warm store fell back {fallbacks} time(s)"
    with open(os.path.join(out_dir, "smoke_serve_warmboot.prom"), "w") as f:
        f.write(scrape)
    return int(hits)


def _strict_prebuilt_scenario(out_dir):
    """ISSUE-16 acceptance: enumerate -> ``aot prebuild --from-surface``
    -> a strict replica boots from the prebuilt store, serves traffic
    spanning every batch/prompt bucket with serve_compile_misses_total
    == 0 and zero fallbacks; then one store entry is deleted and the next
    strict boot fails with a typed AotTraceError (the 503 family), never
    a trace.

    ISSUE-17 addition: the continuous profiler (obs/profile) rides the
    strict replica's mixed traffic — every budgeted decode/prefill
    executable must appear in the capture with nonzero dispatches,
    ``serve_padding_waste_ratio`` must be on the scrape, the derived
    CostProfile lands in $CI_ARTIFACTS_DIR/cost_profile.json AND in the
    prebuilt store (resolved back as a counted profile_store hit — the
    artifact the sim tuner calibrates from at boot)."""
    import glob
    import shutil

    from deeplearning4j_tpu.analysis.__main__ import main as analysis_main
    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.aot.__main__ import main as aot_main
    from deeplearning4j_tpu.models import model_by_name
    from deeplearning4j_tpu.obs.metrics import MetricsRegistry
    from deeplearning4j_tpu.serve import AotTraceError, ModelServer

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    config = json.load(open(os.path.join(repo, "scripts",
                                         "serve_config.json")))
    manifest_path = os.path.join(out_dir, "prebuild_manifest.json")
    if not os.path.exists(manifest_path):
        # ci.sh writes the manifest during its jaxlint step; standalone
        # runs enumerate here (module ids derive from repo-relative paths)
        cwd = os.getcwd()
        os.chdir(repo)
        try:
            rc = analysis_main([
                "deeplearning4j_tpu/serve", "deeplearning4j_tpu/nn",
                "--compile-surface",
                os.path.join(out_dir, "compile_surface.json"),
                "--budget", "scripts/compile_budget.json",
                "--enumerate-manifest", manifest_path,
                "--serve-config", "scripts/serve_config.json"])
        finally:
            os.chdir(cwd)
        assert rc == 0, "enumeration pass failed"

    store_dir = os.path.join(out_dir, "prebuild_store")
    assert aot_main(["--store", store_dir, "prebuild",
                     "--from-surface", manifest_path]) == 0, \
        "prebuild --from-surface failed"
    assert aot_main(["--store", store_dir, "verify",
                     "--manifest", manifest_path]) == 0, \
        "freshly prebuilt store failed its own coverage gate"
    records = glob.glob(os.path.join(store_dir, "coverage", "*.json"))
    assert records, "prebuild stamped no coverage record"
    shutil.copy(records[0], os.path.join(out_dir, "prebuild_coverage.json"))

    gen = config["gen"]

    def boot(store_root, metrics=None):
        model = model_by_name(config["model"], seed=config["seed"],
                              **config["model_kwargs"]).init()
        return ModelServer(
            model, port=0, input_dtype=np.dtype(config["dtype"]),
            batch_buckets=tuple(config["engine"]["batch_buckets"]),
            gen_slots=gen["slots"], gen_capacity=gen["capacity"],
            gen_kv=gen["kv"], gen_block_size=gen["block_size"],
            gen_prefill_chunk=gen["prefill_chunk"], seed=gen["seed"],
            metrics=metrics, aot_store=AotStore(store_root),
            strict_aot=True, aot_manifest=manifest_path)

    from deeplearning4j_tpu.aot import arch_fingerprint
    from deeplearning4j_tpu.obs import profile as prof_mod

    m = MetricsRegistry()
    srv = boot(store_dir, metrics=m).start()
    # the profiler shares the server's registry so profile_* families and
    # the padding-waste gauge ride the same scrape artifact
    prof = prof_mod.install(prof_mod.Profiler(sample_rate=4, metrics=m))
    try:
        model_fp = arch_fingerprint(srv.model.params, srv.model.state)
        rng = np.random.RandomState(7)
        # every batch bucket (1, 2, 4, 8 rows) at the model's native time
        # length — with length_buckets unset that IS the enumerated axis
        for rows in (1, 2, 4, 8):
            ids = rng.randint(0, 50, (rows, 16)).tolist()
            out = _post(srv.port, "/predict", {"ndarray": ids})["output"]
            assert len(out) == rows
        # ... and prompts spanning both prompt buckets (<=8, <=16)
        for plen in (3, 8, 12):
            prompt = rng.randint(0, 50, (plen,)).tolist()
            toks = _post(srv.port, "/generate?stream=false",
                         {"prompt": prompt, "max_new_tokens": 3,
                          "temperature": 0.0})["tokens"]
            assert len(toks) == 3
        debug = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/debug/profile",
            timeout=10).read())
        assert debug.get("enabled") and debug.get("executables"), debug
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read().decode()
    finally:
        snap = prof.snapshot(include_pairs=True)
        prof_mod.uninstall()
        srv.stop()

    # every budgeted decode/prefill executable family took live traffic
    tags = {e["tag"] for e in snap["executables"] if e["dispatches"] > 0}
    assert "engine_forward" in tags, tags
    assert any("prefill" in t for t in tags), tags
    assert any("decode" in t for t in tags), tags
    assert "serve_padding_waste_ratio{" in scrape, \
        "padding-waste gauge missing from strict scrape"
    assert "profile_dispatch_device_seconds" in scrape, \
        "profile histograms missing from strict scrape"

    # the CostProfile artifact: CI upload + AOT-store roundtrip (the
    # tuner-boot path resolves it exactly like this, counted as a hit)
    from deeplearning4j_tpu.obs.costmodel import (ProfileAccumulator,
                                                  get_profile, put_profile)
    cost = ProfileAccumulator().fold(snap).profile()
    with open(os.path.join(out_dir, "cost_profile.json"), "w") as f:
        f.write(cost.to_json())
    assert put_profile(AotStore(store_dir), model_fp, cost) is not None
    m2 = MetricsRegistry()
    got = get_profile(AotStore(store_dir), model_fp, metrics=m2)
    assert got is not None and got.executables, "profile did not roundtrip"
    phits = sum(s["value"] for s in m2.snapshot().get(
        "profile_store_hits_total", {}).get("series", []))
    assert phits == 1, f"profile resolution not counted as a hit: {phits}"

    hits = _prom_total(scrape, "serve_aot_hits_total")
    compiles = _prom_total(scrape, "serve_compile_misses_total")
    fallbacks = _prom_total(scrape, "serve_aot_fallback_total")
    refusals = _prom_total(scrape, "serve_aot_strict_misses_total")
    assert compiles == 0, \
        f"strict prebuilt replica traced ({compiles} compile misses)"
    assert fallbacks == 0, f"strict replica fell back {fallbacks} time(s)"
    assert refusals == 0, f"strict replica refused {refusals} signature(s)"
    assert hits > 0, "strict replica took no AOT store hits"
    with open(os.path.join(out_dir, "smoke_serve_strict.prom"), "w") as f:
        f.write(scrape)

    # delete ONE executable: the next strict boot must fail with the typed
    # error at the manifest gate — before any stack is built, never a trace
    broken = store_dir + "_broken"
    shutil.rmtree(broken, ignore_errors=True)
    shutil.copytree(store_dir, broken)
    victim = glob.glob(os.path.join(broken, "*", "*.aotx"))[0]
    os.remove(victim)
    m = MetricsRegistry()
    try:
        boot(broken, metrics=m).stop()
        raise AssertionError("strict boot served from an incomplete store")
    except AotTraceError as e:
        assert e.http_status == 503 and e.cause == "aot_trace", e
    traced = sum(s["value"] for s in m.snapshot().get(
        "serve_compile_misses_total", {}).get("series", []))
    assert traced == 0, "the refused boot traced instead of failing"
    assert aot_main(["--store", broken, "verify",
                     "--manifest", manifest_path]) == 1, \
        "verify --manifest passed an incomplete store"
    shutil.rmtree(broken, ignore_errors=True)
    return int(hits)


def _fleet_scenario(out_dir):
    """ISSUE-7 acceptance: two named models share an HBM budget that fits
    only ONE, served over the routed fleet front door by two tenants.
    Concurrent cross-model traffic forces page-ins UNDER LOAD and every
    response must still match its own model (zero wrong-params answers);
    the throttled tenant's sheds surface as HTTP 429 + Retry-After and as
    ``serve_shed_total{cause="quota",tenant=...}`` on the shared scrape,
    which lands in $CI_ARTIFACTS_DIR as smoke_serve_fleet.prom."""
    import urllib.error

    import jax

    from deeplearning4j_tpu.fleet import FleetRegistry, FleetServer
    from deeplearning4j_tpu.models import CausalLM

    models = {}
    for name, seed in (("alpha", 0), ("beta", 1)):
        m = CausalLM(seed=seed, input_shape=(16,), num_layers=2, d_model=32,
                     num_heads=4, vocab=50).build()
        m.init()
        models[name] = m
    wb = sum(int(np.asarray(leaf).nbytes) for leaf in
             jax.tree.leaves((models["alpha"].params,
                              models["alpha"].state)))
    fleet = FleetRegistry(hbm_budget_bytes=wb + wb // 2)  # one resident
    for name, m in models.items():
        fleet.add(name, m, input_dtype=np.int32,
                  engine_opts={"batch_buckets": (1, 2, 4)})
    fleet.tenants.register("pro", rate_per_s=500, slo="standard")
    fleet.tenants.register("free", rate_per_s=1.0, burst=2.0, slo="batch")
    srv = FleetServer(fleet, port=0).start()
    try:
        rng = np.random.RandomState(3)
        prompts = rng.randint(0, 50, (4, 2, 16)).astype(np.int32)
        refs = {n: [np.asarray(m.output(p)) for p in prompts]
                for n, m in models.items()}

        def post(name, j, tenant):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/models/{name}/predict",
                data=json.dumps({"ndarray": prompts[j].tolist()}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Tenant": tenant})
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        # interleaved cross-model traffic: every round trips a page cycle,
        # and the paging happens while other requests are in flight
        jobs = [(("alpha", "beta")[i % 2], i % len(prompts))
                for i in range(12)]
        with cf.ThreadPoolExecutor(4) as ex:
            outs = list(ex.map(lambda nj: (nj, post(*nj, "pro")), jobs))
        for (name, j), reply in outs:
            assert reply["model"] == name
            np.testing.assert_allclose(
                np.asarray(reply["output"]), refs[name][j],
                rtol=1e-4, atol=1e-5,
                err_msg=f"wrong-params response from {name}")

        # quota tenant: the bucket admits the burst, then 429 + Retry-After
        quota = []
        for _ in range(6):
            try:
                post("alpha", 0, "free")
                quota.append(200)
            except urllib.error.HTTPError as e:
                body = json.loads(e.read())
                quota.append((e.code, body["cause"],
                              e.headers.get("Retry-After")))
        sheds = [q for q in quota if q != 200]
        assert 200 in quota and sheds, quota
        assert all(q[0] == 429 and q[1] == "quota" and int(q[2]) >= 1
                   for q in sheds), quota

        status = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/fleet", timeout=10).read())
        page_ins = status["pager"]["page_ins"]
        assert page_ins >= 3, status["pager"]  # paging happened under load
        assert status["tenants"]["free"]["shed"] >= 1, status["tenants"]

        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read().decode()
        for needle in ('serve_shed_total{cause="quota"', 'tenant="free"',
                       "fleet_page_in_total{model=", "fleet_page_out_total",
                       "fleet_resident_bytes", "fleet_hbm_budget_bytes",
                       'serve_lease_total{model='):
            assert needle in scrape, f"missing {needle} in fleet /metrics"
        with open(os.path.join(out_dir, "smoke_serve_fleet.prom"), "w") as f:
            f.write(scrape)
        return page_ins, len(sheds)
    finally:
        srv.stop()


def main() -> int:
    out_dir = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(out_dir, exist_ok=True)

    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.nn.generation import generate
    from deeplearning4j_tpu.obs import reqtrace as reqtrace_mod
    from deeplearning4j_tpu.obs.reqtrace import RequestTracer
    from deeplearning4j_tpu.serve import ModelServer

    # request tracing on for the whole run: every histogram observation in
    # the serving path carries its request's trace_id, so the OpenMetrics
    # artifact below must come out exemplar-bearing
    reqtrace_mod.install(RequestTracer())

    model = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                     num_heads=4, vocab=50).build()
    model.init()
    srv = ModelServer(model, port=0, input_dtype=np.int32,
                      batch_buckets=(1, 2, 4, 8), gen_slots=2,
                      gen_capacity=16).start()
    try:
        rng = np.random.RandomState(0)
        jobs = []
        for _ in range(PREDICTS):
            ids = rng.randint(0, 50, (int(rng.randint(1, 5)), 8)).tolist()
            jobs.append(("/predict", {"ndarray": ids}))
        for _ in range(GENERATES):
            prompt = rng.randint(0, 50, (int(rng.randint(3, 9)),)).tolist()
            jobs.append(("/generate?stream=false",
                         {"prompt": prompt, "max_new_tokens": 4,
                          "temperature": 0.0}))
        rng.shuffle(jobs)
        with cf.ThreadPoolExecutor(8) as ex:
            replies = list(ex.map(lambda j: (j, _post(srv.port, *j)), jobs))
        assert len(replies) == PREDICTS + GENERATES, "dropped responses"

        # greedy /generate is bit-identical to whole-batch generation
        for (path, body), reply in replies:
            if path == "/predict":
                want = np.asarray(model.output(
                    np.asarray(body["ndarray"], np.int32)))
                np.testing.assert_allclose(np.asarray(reply["output"]), want,
                                           rtol=1e-4, atol=1e-5)
            else:
                want = generate(model, np.asarray([body["prompt"]], np.int32),
                                4, temperature=0.0)[0]
                assert reply["tokens"] == want.tolist(), \
                    (path, body, reply, want)

        # default /generate streams SSE, token-identical to the buffered path
        sse_prompt = rng.randint(0, 50, (7,)).tolist()
        sse_body = {"prompt": sse_prompt, "max_new_tokens": 4,
                    "temperature": 0.0}
        sse_toks = _sse_generate(srv.port, sse_body)
        assert sse_toks == _post(srv.port, "/generate?stream=false",
                                 sse_body)["tokens"], "SSE != buffered"

        # bounded executables: engine <= |batch buckets|, batcher <=
        # |prompt buckets| + one decode step
        n_eng = len(srv.engine.compile_signatures)
        assert n_eng <= 4, srv.engine.compile_signatures
        bat = srv.batcher()
        n_gen = len(bat.compile_signatures)
        assert n_gen <= len(bat.prompt_buckets) + 1, bat.compile_signatures

        # long-prompt burst overcommitting a tiny pool (separate batcher so
        # the server's own pool sizing is untouched)
        pool_blocks = _overcommit_burst(model)

        # shared-system-prompt burst: cache hits, zero new compiles,
        # bit-identical decode, refcounts drain to zero after flush
        px_hits, px_saved = _prefix_cache_scenario(model)

        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/health", timeout=10).read())
        assert health["status"] == "ok"
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read().decode()
        for needle in REQUIRED_METRICS:
            assert needle in scrape, f"missing {needle} in /metrics"

        prom_path = os.path.join(out_dir, "smoke_serve_metrics.prom")
        with open(prom_path, "w") as f:
            f.write(scrape)
        # OpenMetrics negotiation: same registry, exemplar-bearing syntax
        om = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/metrics",
            headers={"Accept": "application/openmetrics-text"}),
            timeout=10).read().decode()
        assert om.rstrip("\n").endswith("# EOF"), "OM scrape not terminated"
        assert '# {trace_id="' in om, "no exemplars in OpenMetrics scrape"
        with open(os.path.join(out_dir, "smoke_serve_metrics_om.prom"),
                  "w") as f:
            f.write(om)
        print(f"smoke_serve: {PREDICTS} predicts + {GENERATES} generates "
              f"+ SSE + overcommit burst ({pool_blocks}-block pool) "
              f"+ prefix-cache burst ({px_hits} hits, {px_saved} prompt "
              f"tokens saved), {n_eng} engine compile(s), {n_gen} generate "
              f"compile(s), generation {health['generation']} -> {prom_path}")
    finally:
        srv.stop()

    # cold-start acceptance: second boot against a warm AOT store serves
    # with zero XLA compiles
    aot_hits = _aot_warm_boot(out_dir)
    print(f"smoke_serve: warm second boot served from the AOT store "
          f"({aot_hits} executable loads, 0 compiles)")

    # prebuild-farm acceptance: enumerated manifest -> prebuilt store ->
    # strict replica with zero compile misses; incomplete store = typed
    # boot failure
    strict_hits = _strict_prebuilt_scenario(out_dir)
    print(f"smoke_serve: strict prebuilt replica OK — {strict_hits} store "
          f"loads, 0 compiles, incomplete store refused with AotTraceError; "
          f"cost profile captured -> cost_profile.json (+ store roundtrip)")

    # fleet acceptance: two models sharing a one-model budget, two tenants,
    # page-ins under load, quota sheds on the scrape
    page_ins, quota_sheds = _fleet_scenario(out_dir)
    print(f"smoke_serve: fleet scenario OK — {page_ins} page-ins under "
          f"load, {quota_sheds} quota shed(s) with Retry-After")

    reqtrace_mod.uninstall()

    # every scrape artifact this run wrote must survive the exposition
    # validator — a scrape Prometheus would reject is worse than none
    import glob

    from deeplearning4j_tpu.obs.promcheck import check_file

    paths = sorted(glob.glob(os.path.join(out_dir, "smoke_serve*.prom")))
    assert paths, "no scrape artifacts written"
    bad = {p: check_file(p)[:3] for p in paths if check_file(p)}
    assert not bad, f"invalid scrape artifacts: {bad}"
    print(f"smoke_serve: promcheck OK over {len(paths)} scrape artifact(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
