#!/usr/bin/env python
"""Probe 2: HLO dtype audit + batch-256 throughput.

Checks the compiled train step for f32 convolutions (mixed-precision leaks)
and measures throughput at BENCH_BATCH (default 256).
"""

import os
import re
import time

import jax
import numpy as np

from deeplearning4j_tpu.data import BenchmarkIterator
from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.train import Trainer

dev = jax.devices()[0]
on_tpu = dev.platform != "cpu"
batch = int(os.environ.get("BENCH_BATCH", 256 if on_tpu else 4))
img = int(os.environ.get("BENCH_IMG", 224 if on_tpu else 32))

zm = ResNet50(num_classes=1000, seed=0, input_shape=(img, img, 3))
model = zm.build()
if on_tpu:
    model.config.compute_dtype = "bfloat16"
model.init()

tr = Trainer(model)
step = tr._make_step()
it = BenchmarkIterator((img, img, 3), 1000, batch, 1)
ds = next(iter(it))
x = jax.device_put(np.asarray(ds.features))
y = jax.device_put(np.asarray(ds.labels))
rng = jax.random.PRNGKey(0)
params, opt_state, state = tr.params, tr.opt_state, tr.state

lowered = step.lower(params, opt_state, state, x, y, rng)
hlo = lowered.as_text()
convs = re.findall(r"(\S+) = (\S+) convolution\(", hlo)
from collections import Counter

dtypes = Counter(re.match(r"([a-z0-9]+)\[", t).group(1) for _, t in convs if re.match(r"([a-z0-9]+)\[", t))
print(f"convolutions by output dtype: {dict(dtypes)}  (total {len(convs)})")
dots = re.findall(r" = (\S+) dot\(", hlo)
ddt = Counter(re.match(r"([a-z0-9]+)\[", t).group(1) for t in dots if re.match(r"([a-z0-9]+)\[", t))
print(f"dots by output dtype: {dict(ddt)}")
# f32 convolution operand check: find conv lines with f32 operands
f32conv = [line for line in hlo.splitlines() if "convolution(" in line and "f32[" in line.split("convolution(")[0]]
print(f"conv defs with f32 output: {len(f32conv)}")
for line in f32conv[:6]:
    print("  ", line.strip()[:160])

compiled = lowered.compile()
ca = compiled.cost_analysis()
if isinstance(ca, list):
    ca = ca[0]
print(f"flops/step @batch{batch}: {ca.get('flops', 0):.3e} ({ca.get('flops', 0)/batch:.3e}/img)")

def run(k, params, opt_state, state):
    t0 = time.perf_counter()
    for _ in range(k):
        params, opt_state, state, loss = step(params, opt_state, state, x, y, rng)
    lf = float(loss)
    return time.perf_counter() - t0, params, opt_state, state

_, params, opt_state, state = run(3, params, opt_state, state)
t1, params, opt_state, state = run(5, params, opt_state, state)
t2, params, opt_state, state = run(15, params, opt_state, state)
per_step = (t2 - t1) / 10
ips = batch / per_step
mfu = ips * 3 * 8.18e9 * (img / 224.0) ** 2 / 197e12
print(f"batch {batch}: {per_step*1e3:.2f} ms/step, {ips:.1f} img/s, MFU(2/MAC)={mfu:.3f}")
