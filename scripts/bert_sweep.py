#!/usr/bin/env python
"""Sweep BERT-base fine-tune batch/seq/flash on the real chip to find the
best MFU point; goal: >=0.70 MFU (the declared north-star carrier after
the ResNet conv/BN envelope analysis, PERF.md r3). Flash variants matter:
at T=512 the (B, 12, 512, 512) attention tensors are the non-matmul tax
the Pallas kernel removes."""
import json
import sys

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

from chiputil import smoke_or_probe

SMOKE = smoke_or_probe()

import model_benches as mb
from deeplearning4j_tpu.models import BertBase

CONFIGS = ([(2, 128, True), (2, 128, False)] if SMOKE else
           [(128, 128, False), (256, 128, False), (256, 128, True),
            (32, 512, False), (64, 512, False),
            (32, 512, True), (64, 512, True), (128, 512, True)])

results = {}
for batch, T, flash in CONFIGS:
    name = f"bert_b{batch}_t{T}" + ("_flash" if flash else "")
    try:
        r = mb.bench_model(
            name,
            lambda T=T, flash=flash: BertBase(num_classes=2, seed=0,
                                              input_shape=(T,), flash=flash).build(),
            batch, (T,), 2, token_vocab=30522, on_tpu=not SMOKE,
            steps=2 if SMOKE else 20)
        results[name] = r
        print(json.dumps(r), flush=True)
    except Exception as e:
        results[name] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(f"{name}: ERROR {results[name]['error']}", flush=True)

with open("/tmp/bert_sweep_results.json", "w") as f:
    json.dump(results, f, indent=1)
print("DONE -> /tmp/bert_sweep_results.json")
