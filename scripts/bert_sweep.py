#!/usr/bin/env python
"""Sweep BERT-base fine-tune batch sizes (and seq lens) on the real chip to
find the best MFU point; goal: >=0.70 MFU (north-star) on this config."""
import json
import os
import sys

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

import model_benches as mb
from deeplearning4j_tpu.models import BertBase

results = {}
for batch, T, flash in [(128, 128, False), (256, 128, False),
                        (32, 512, False), (64, 512, False)]:
    name = f"bert_b{batch}_t{T}" + ("_flash" if flash else "")
    try:
        r = mb.bench_model(
            name,
            lambda T=T, flash=flash: BertBase(num_classes=2, seed=0,
                                              input_shape=(T,), flash=flash).build(),
            batch, (T,), 2, token_vocab=30522, on_tpu=True)
        results[name] = r
        print(json.dumps(r), flush=True)
    except Exception as e:
        print(f"{name}: {type(e).__name__}: {str(e)[:200]}", flush=True)

print(json.dumps(results, indent=1))
