#!/usr/bin/env bash
# One-command test runner with tiers (r4 VERDICT #6; ref runtests.sh:34).
#
#   scripts/run_tests.sh fast      ~3.5 min  quick sanity (14 suites)
#   scripts/run_tests.sh slow      ~26 min   compile-heavy suites (14)
#   scripts/run_tests.sh examples  ~4 min    runnable-examples smoke
#   scripts/run_tests.sh all       ~33 min   everything (default)
#
# Tier membership comes from a measured per-file timing pass (r5,
# /tmp/per_file_times.log methodology: each file timed alone on an
# otherwise idle host; fast = files <= ~35s). Every tier prints ONE
# summary line `TIER <name>: <pytest tail> (<wall>s)` and the script
# exits nonzero if any tier fails. A full log lands in
# scripts/logs/run_tests_last.log.
set -u
cd "$(dirname "$0")/.."

FAST="tests/test_clustering.py tests/test_custom_layer.py tests/test_data.py \
tests/test_eval.py tests/test_knn_graph_tsne.py tests/test_native.py \
tests/test_nlp.py tests/test_ops.py tests/test_orbax.py \
tests/test_provision.py tests/test_solvers.py tests/test_streaming_ml.py \
tests/test_transfer.py tests/test_ui.py"

SLOW="tests/test_dryrun_entry.py tests/test_flash_attention.py \
tests/test_generation.py tests/test_keras_import.py tests/test_layers.py \
tests/test_model.py tests/test_moe.py tests/test_multihost.py \
tests/test_parallel.py tests/test_pipeline.py tests/test_pretrained.py \
tests/test_sharding_api.py tests/test_train.py tests/test_zoo.py"

EXAMPLES="tests/test_examples.py"

mkdir -p scripts/logs
LOG=scripts/logs/run_tests_last.log
: > "$LOG"

# completeness guard: a test file outside every tier would silently never
# run through this entry point
for f in tests/test_*.py; do
    case " $FAST $SLOW $EXAMPLES " in
        *" $f "*) ;;
        *) echo "ERROR: $f is not assigned to a tier in $0" >&2; exit 2 ;;
    esac
done

run_tier() {
    local name="$1"; shift
    local t0 t1 tail rc mark
    # only look at lines THIS tier appended — otherwise a tier that dies
    # before printing a pytest summary would report the previous tier's
    mark=$(wc -l < "$LOG")
    t0=$(date +%s)
    python -m pytest $@ -q >> "$LOG" 2>&1
    rc=$?
    t1=$(date +%s)
    tail=$(tail -n +"$((mark + 1))" "$LOG" \
           | grep -E "[0-9]+ (passed|failed|error)" | tail -1)
    echo "TIER ${name}: ${tail:-no-summary} ($((t1 - t0))s, rc=${rc})"
    return $rc
}

tier="${1:-all}"
status=0
case "$tier" in
    fast)     run_tier fast $FAST || status=1 ;;
    slow)     run_tier slow $SLOW || status=1 ;;
    examples) run_tier examples $EXAMPLES || status=1 ;;
    all)
        run_tier fast $FAST || status=1
        run_tier slow $SLOW || status=1
        run_tier examples $EXAMPLES || status=1
        ;;
    *) echo "usage: $0 [fast|slow|examples|all]" >&2; exit 2 ;;
esac
exit $status
