#!/usr/bin/env python
"""On-chip sweeps: 738M grad_accum A/B, char-RNN scan_unroll, LeNet spe,
BERT T=512 flash. Probe-guarded; each job fenced; sized to finish."""
import json
import sys

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

from chiputil import smoke_or_probe

SMOKE = smoke_or_probe()

import model_benches as mb
from deeplearning4j_tpu.models import BertBase, GravesLSTMCharRNN, LeNet

SMOKE_JOBS = [
    # same code paths at toy sizes (megastep spe, scan_unroll, micro
    # grad_accum, BERT flash) — the pre-window shakeout
    ("smoke_transformer_micro2", lambda: mb.bench_transformer(
        num_layers=2, d_model=64, batch=2, seq=32, vocab=128, flash=False,
        steps=2, micro=2)),
    ("smoke_charrnn_u4", lambda: mb.bench_model(
        "smoke_charrnn_u4", lambda: GravesLSTMCharRNN(
            seed=0, tbptt=0, scan_unroll=4).build(),
        8, (16, 98), 98, seq=True, spe=2, steps=2, on_tpu=False)),
    ("smoke_lenet_spe", lambda: mb.bench_model(
        "smoke_lenet_spe", lambda: LeNet(num_classes=10, seed=0,
                                         input_shape=(28, 28, 1)).build(),
        16, (28, 28, 1), 10, spe=2, steps=2, on_tpu=False)),
    ("smoke_bert_flash", lambda: mb.bench_model(
        "smoke_bert_flash", lambda: BertBase(
            small=True, num_classes=2, seed=0, input_shape=(128,),
            flash=True).build(),
        2, (128,), 2, token_vocab=1000, steps=2, on_tpu=False)),
]

JOBS = [
    # 738M: optimizer-amortization A/B (batch 4 microbatch, 1/2/4 accum)
    ("738m_micro1", lambda: mb.bench_transformer(d_model=2048, batch=4,
                                                 flash=True, micro=1, steps=10)),
    ("738m_micro2", lambda: mb.bench_transformer(d_model=2048, batch=4,
                                                 flash=True, micro=2, steps=8)),
    ("738m_micro4", lambda: mb.bench_transformer(d_model=2048, batch=4,
                                                 flash=True, micro=4, steps=6)),
    # char-RNN: scan_unroll sweep at spe=8
    ("charrnn_u1", lambda: mb.bench_model(
        "charrnn_u1", lambda: GravesLSTMCharRNN(seed=0, tbptt=0).build(),
        128, (64, 98), 98, seq=True, spe=8)),
    ("charrnn_u4", lambda: mb.bench_model(
        "charrnn_u4", lambda: GravesLSTMCharRNN(seed=0, tbptt=0,
                                                scan_unroll=4).build(),
        128, (64, 98), 98, seq=True, spe=8)),
    ("charrnn_u8", lambda: mb.bench_model(
        "charrnn_u8", lambda: GravesLSTMCharRNN(seed=0, tbptt=0,
                                                scan_unroll=8).build(),
        128, (64, 98), 98, seq=True, spe=8)),
    # LeNet megastep capture
    ("lenet_spe16", lambda: mb.bench_model(
        "lenet_spe16",
        lambda: LeNet(num_classes=10, seed=0, input_shape=(28, 28, 1)).build(),
        1024, (28, 28, 1), 10, spe=16)),
    # VGG16 (138M params): optimizer-amortization A/B via grad_accum
    ("vgg16_micro2", lambda: mb.bench_model(
        "vgg16_micro2",
        lambda: __import__("deeplearning4j_tpu.models", fromlist=["VGG16"]
                           ).VGG16(num_classes=1000, seed=0,
                                   input_shape=(224, 224, 3)).build(),
        32, (224, 224, 3), 1000, micro=2, steps=10)),
    # BERT T=512: flash vs dense attention
    ("bert_t512_dense", lambda: mb.bench_model(
        "bert_t512_dense",
        lambda: BertBase(num_classes=2, seed=0, input_shape=(512,)).build(),
        32, (512,), 2, token_vocab=30522)),
    ("bert_t512_flash", lambda: mb.bench_model(
        "bert_t512_flash",
        lambda: BertBase(num_classes=2, seed=0, input_shape=(512,),
                         flash=True).build(),
        32, (512,), 2, token_vocab=30522)),
]

def bench_bert_inference(batch=64, T=128, iters=30):
    """Forward-only (serving) throughput, bf16 — the ParallelInference
    surface's device ceiling."""
    import time

    import jax
    import numpy as np

    from deeplearning4j_tpu.models import BertBase
    from deeplearning4j_tpu.train.trainer import make_infer_fn

    m = BertBase(num_classes=2, seed=0, input_shape=(T,)).build()
    m.config.compute_dtype = "bfloat16"
    m.init()
    infer = make_infer_fn(m)
    x = jax.device_put(np.random.RandomState(0).randint(
        0, 30522, (batch, T)).astype(np.int32))
    import jax.numpy as jnp

    @jax.jit
    def step(x, carry):
        # chain iterations through a value-neutral carry so one final D2H
        # readback syncs the whole loop (block_until_ready lies through
        # the tunnel; per-iteration readback pays RTT every step)
        r = infer(m.params, m.state, x + (carry * 0).astype(x.dtype), None)
        leaf = jax.tree.leaves(r)[0]
        return jnp.sum(leaf.astype(jnp.float32))

    carry = jnp.float32(0)
    carry = step(x, carry)
    float(carry)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = step(x, carry)
    float(carry)
    dt = (time.perf_counter() - t0) / iters
    return {"model": f"bert_infer_b{batch}_t{T}", "batch": batch,
            "step_ms": round(dt * 1e3, 2),
            "samples_per_sec": round(batch / dt, 1)}


if SMOKE:
    JOBS = SMOKE_JOBS
else:
    JOBS.append(("bert_infer", bench_bert_inference))

results = {}
for name, fn in JOBS:
    try:
        results[name] = fn()
        print(name, json.dumps(results[name]), flush=True)
    except Exception as e:
        results[name] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(name, "ERROR", results[name]["error"], flush=True)

with open("/tmp/chip_sweeps_results.json", "w") as f:
    json.dump(results, f, indent=1)
print("DONE -> /tmp/chip_sweeps_results.json")
