#!/usr/bin/env python
"""Build the Korean morpheme lexicon for the eojeol-Viterbi tokenizer
(r4 VERDICT #4: replace the josa suffix heuristic with a morpheme lexicon
+ Viterbi, the OpenKoreanText-class design).

Sources (all offline):

1. MINED Sino-Korean nouns — ~60% of Korean vocabulary is hanja
   compounds with fully systematic per-character readings (經濟→경제).
   The table below maps simplified-Chinese characters (jieba dict.txt's
   script) to their Korean readings; the initial-sound rule (두음법칙)
   is applied to the first syllable (라→나, 려→여, 니→이 classes).
   Characters without a confident single reading drop the word. Mined
   words enter at discounted frequencies.
2. AUTHORED — nlp/data/ko_base_vocab.txt: knowledge-written native
   Korean vocabulary (nouns, adverbs, determiners) with frequency bands.
   Never tuned on tests/data/cjk_gold_ko.txt.

Output: deeplearning4j_tpu/nlp/data/ko_lexicon.txt ("word freq" lines).

--tune: grid-search the tokenizer's penalties on tests/data/cjk_dev_ko.txt
— a dev set authored SEPARATELY from (and after) the r4 gold, used only
for tuning so the gold measurement stays untouched.
"""

import os
import sys
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "deeplearning4j_tpu", "nlp", "data",
                   "ko_lexicon.txt")
VOCAB = os.path.join(REPO, "deeplearning4j_tpu", "nlp", "data",
                     "ko_base_vocab.txt")
DEV = os.path.join(REPO, "tests", "data", "cjk_dev_ko.txt")

# simplified-Chinese char -> Korean reading (hangul). One confident
# reading per char; ambiguous chars (金 김/금, 车 차/거, 宅 댁/택 ...)
# are either given their compound-dominant reading or omitted.
ZH2KO = {}
for pair in (
    "爱애 安안 案안 暗암 压압 野야 约약 药약 养양 阳양 洋양 样양 扬양 "
    "语어 鱼어 渔어 亿억 忆억 言언 业업 余여 旅여 与여 易이 域역 驿역 "
    "役역 研연 然연 烟연 延연 演연 热열 盐염 炎염 荣영 英영 永영 迎영 映영 "
    "营영 预예 艺예 礼예 例예 誉예 五오 午오 误오 屋옥 温온 完완 王왕 "
    "外외 要요 曜요 用용 勇용 容용 友우 雨우 右우 优우 邮우 云운 运운 "
    "雄웅 元원 原원 远원 园원 院원 员원 愿원 源원 月월 越월 位위 危위 "
    "委위 伟위 卫위 油유 由유 有유 幼유 遗유 育육 肉육 银은 恩은 音음 "
    "饮음 阴음 应응 意의 医의 衣의 依의 议의 义의 二이 移이 以이 异이 "
    "益익 人인 引인 印인 认인 因인 一일 日일 任임 入입 子자 字자 自자 "
    "者자 姿자 资자 作작 昨작 残잔 暂잠 杂잡 长장 场장 章장 将장 壮장 "
    "装장 张장 才재 材재 财재 再재 在재 灾재 争쟁 低저 底저 贮저 的적 "
    "赤적 适적 敌적 积적 绩적 电전 前전 全전 战전 传전 专전 转전 钱전 "
    "展전 店점 点점 接접 定정 正정 政정 情정 精정 程정 整정 庭정 停정 "
    "订정 静정 弟제 第제 题제 制제 提제 济제 际제 祭제 除제 助조 组조 "
    "调조 造조 朝조 条조 早조 足족 族족 存존 尊존 卒졸 种종 终종 从종 "
    "钟종 坐좌 左좌 罪죄 主주 住주 注주 周주 州주 酒주 昼주 竹죽 准준 "
    "中중 重중 众중 即즉 增증 证증 症증 地지 知지 指지 持지 志지 至지 "
    "支지 纸지 直직 职직 织직 进진 真진 振진 阵진 质질 集집 执집 车차 "
    "次차 差차 着착 察찰 参참 唱창 窗창 创창 菜채 采채 册책 责책 处처 "
    "天천 千천 川천 浅천 铁철 哲철 清청 青청 请청 厅청 听청 体체 替체 "
    "初초 草초 招초 秒초 村촌 总총 最최 追추 秋추 推추 祝축 建축 筑축 "
    "蓄축 春춘 出출 充충 忠충 虫충 取취 就취 趣취 测측 侧측 层층 治치 "
    "致치 齿치 值치 置치 则칙 亲친 七칠 针침 称칭 快쾌 他타 打타 卓탁 "
    "炭탄 弹탄 脱탈 探탐 太태 态태 泰태 土토 通통 统통 痛통 退퇴 投투 "
    "特특 波파 派파 破파 判판 板판 版판 八팔 败패 便편 片편 篇편 编편 "
    "平평 评평 闭폐 包포 布포 报보 保보 步보 补보 宝보 普보 福복 服복 "
    "复복 本본 奉봉 部부 父부 夫부 富부 妇부 副부 负부 北북 分분 不불 "
    "佛불 比비 非비 飞비 备비 费비 鼻비 悲비 批비 秘비 贫빈 氷빙 "
    "四사 事사 思사 死사 私사 师사 士사 史사 使사 查사 社사 写사 谢사 "
    "辞사 司사 产산 山산 算산 散산 三삼 上상 相상 想상 常상 商상 赏상 "
    "状상 象상 像상 色색 生생 西서 书서 序서 暑서 石석 席석 夕석 先선 "
    "线선 选선 鲜선 船선 宣선 善선 说설 设설 雪설 性성 成성 城성 诚성 "
    "盛성 声성 星성 圣성 姓성 世세 势세 洗세 税세 细세 小소 少소 所소 "
    "消소 素소 笑소 续속 速속 束속 属속 孙손 损손 松송 送송 水수 手수 "
    "受수 授수 首수 数수 树수 收수 修수 秀수 宿숙 顺순 纯순 术술 习습 "
    "拾습 胜승 乘승 承승 升승 市시 时시 始시 示시 视시 试시 诗시 施시 "
    "食식 式식 植식 识식 新신 信신 身신 神신 申신 失실 实실 室실 心심 "
    "深심 十십 氏씨 儿아 我아 牙아 恶악 乐악 眼안 颜안 岸안 爱애 液액 "
    "额액 夜야 弱약 若약 量량 良량 两량 旅려 力력 历력 连련 练련 恋련 "
    "列렬 令령 领령 例례 老로 路로 劳로 录록 论론 料료 龙룡 流류 类류 "
    "留류 六륙 陆륙 轮륜 律률 率률 利리 理리 里리 离리 林림 立립 "
    "马마 晚만 万만 满만 末말 亡망 望망 忘망 每매 买매 卖매 妹매 脉맥 "
    "面면 免면 勉면 名명 明명 命명 鸣명 母모 毛모 模모 木목 目목 牧목 "
    "梦몽 墓묘 妙묘 无무 武무 务무 舞무 贸무 门문 文문 问문 闻문 物물 "
    "米미 美미 味미 未미 民민 密밀 朴박 博박 半반 反반 班반 发발 方방 "
    "房방 防방 放방 访방 拜배 倍배 配배 白백 百백 番번 烦번 犯범 范범 "
    "法법 变변 边변 辩변 别별 病병 兵병 并병 "
    "家가 加가 价가 可가 歌가 街가 假가 各각 角각 觉각 间간 看간 简간 "
    "感감 减감 监감 敢감 甲갑 江강 强강 讲강 康강 降강 钢강 改개 个개 "
    "开개 客객 去거 巨거 拒거 据거 居거 车거 健건 建건 件건 乾건 检검 "
    "格격 击격 激격 犬견 见견 坚견 决결 结결 缺결 京경 经경 庆경 竞경 "
    "境경 警경 轻경 倾경 镜경 景경 敬경 惊경 计계 界계 系계 季계 鸡계 "
    "继계 阶계 古고 告고 高고 苦고 考고 固고 故고 孤고 库고 曲곡 谷곡 "
    "困곤 骨골 工공 公공 共공 功공 空공 攻공 供공 科과 果과 课과 过과 "
    "官관 观관 关관 管관 馆관 光광 广광 校교 教교 交교 桥교 九구 口구 "
    "求구 救구 究구 久구 旧구 具구 区구 句구 构구 国국 局국 菊국 军군 "
    "君군 郡군 群군 屈굴 宫궁 穷궁 权권 券권 拳권 贵귀 归귀 规규 均균 "
    "极극 剧극 克극 近근 勤근 根근 今금 禁금 急급 级급 给급 气기 记기 "
    "期기 基기 技기 几기 己기 起기 其기 器기 机기 既기 纪기 吉길 "
    "暖난 难난 南남 男남 内내 女녀 年년 念념 怒노 农농 脑뇌 能능 "
    "泥니 多다 茶다 短단 团단 段단 单단 断단 端단 但단 达달 谈담 担담 "
    "答답 堂당 当당 党당 大대 代대 对대 待대 队대 带대 贷대 德덕 图도 "
    "道도 岛도 到도 度도 都도 徒도 导도 毒독 独독 读독 东동 冬동 同동 "
    "动동 童동 铜동 头두 豆두 得득 等등 登등 灯등 "
    "学학 为위 行행 会회 于우 下하 后후 现현 化화 如여 表표 合합 海해 "
    "品품 汉한 湖호 好호 形형 回회 省성 活활 解해 金금 府부 何하 联련 "
    "华화 河하 风풍 皇황 举거 候후 革혁 话화 必필 黄황 花화 许허 向향 "
    "影영 况황 帝제 息식 企기 县현 台대 火화 型형 和화 标표 般반 股고 "
    "需수 往왕 响향 亚아 红홍 显현 洲주 节절 项항 照조 严엄 切절 护호 "
    "兴흥 效효 围위 走주 更경 双쌍 验험 环환 航항 落락 斗투 协협 维유 "
    "刻각 较교 似사 抗항 罗라 央앙 策책 审심 限한 须수 括괄 害해 获획 "
    "紧긴 排배 宗종 户호 号호 苏소 射사 征정 超초 止지 绝절 略략 玉옥 "
    "冲충 微미 昌창 血혈 封봉 沙사 黑흑 喜희 尽진 伤상 乡향 销소 临림 "
    "兰란 欧구 核핵 陈진 著저 宜의 否부 希희 典전 威위 础초 词사 夏하 "
    "尚상 镇진 刚강 介개 楼루 座좌 述술 呼호 胡호 训훈 香향 洪홍 诉소 "
    "险험 奇기 之지 已이 及급 来래 是시 未미 永영 由유 风풍 阵진 康강 "
    "境경 另령 布포 巨거 倒도 候후 选선 单단 团단 归귀 弹탄 强강 断단 "
    "收수 旧구 礼례 乱란 灵령 隆륭 陵릉 绿록 "
).split():
    if len(pair) == 2:
        ZH2KO.setdefault(pair[0], pair[1])

# initial-sound rule (두음법칙): applied to the FIRST syllable of a word.
_DUEUM = {"라": "나", "락": "낙", "란": "난", "람": "남", "랑": "낭",
          "래": "내", "랭": "냉", "로": "노", "록": "녹", "론": "논",
          "롱": "농", "뢰": "뇌", "루": "누", "릉": "능",
          "략": "약", "량": "양", "려": "여", "력": "역", "련": "연",
          "렬": "열", "렴": "염", "렵": "엽", "령": "영", "례": "예",
          "료": "요", "룡": "용", "류": "유", "륙": "육", "륜": "윤",
          "률": "율", "리": "이", "린": "인", "림": "임", "립": "입",
          "녀": "여", "뇨": "요", "뉴": "유", "니": "이", "닉": "익"}


def _is_han(w):
    return all(0x4E00 <= ord(c) <= 0x9FFF for c in w)


def _is_hangul(w):
    return all(0xAC00 <= ord(c) <= 0xD7AF for c in w)


def mine_sino_korean():
    out = Counter()
    try:
        import jieba
    except ImportError:
        return out
    dict_path = os.path.join(os.path.dirname(jieba.__file__), "dict.txt")
    for line in open(dict_path, encoding="utf-8"):
        parts = line.split()
        if len(parts) < 2 or not _is_han(parts[0]):
            continue
        w, f = parts[0], int(parts[1])
        if len(w) < 2 or len(w) > 4 or f < 50:
            continue
        syls = []
        ok = True
        for c in w:
            r = ZH2KO.get(c)
            if r is None:
                ok = False
                break
            syls.append(r)
        if not ok:
            continue
        syls[0] = _DUEUM.get(syls[0], syls[0])
        ko = "".join(syls)
        out[ko] = max(out[ko], min(150, max(3, f // 200)))
    return out


def build(write=True):
    freqs = Counter()
    n_auth = 0
    if os.path.exists(VOCAB):
        for line in open(VOCAB, encoding="utf-8"):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            w, f = parts[0], int(parts[1])
            if f > 0 and _is_hangul(w):
                freqs[w] = max(freqs[w], f)
                n_auth += 1
    mined = mine_sino_korean()
    n_mined = 0
    for w, f in mined.items():
        if w not in freqs:
            n_mined += 1
            freqs[w] = f
    if write:
        entries = sorted(freqs.items(), key=lambda kv: (-kv[1], kv[0]))
        with open(OUT, "w", encoding="utf-8") as f:
            f.write(
                "# Generated by scripts/grow_ko_lexicon.py. Sources:\n"
                "#  - knowledge-authored ko_base_vocab.txt,\n"
                "#  - Sino-Korean compounds mined from jieba dict.txt via\n"
                "#    the per-character hanja-reading table + 두음법칙\n"
                "#    (discounted frequencies).\n"
                "# Format: word<space>frequency per line.\n")
            f.write("\n".join(f"{w} {fr}" for w, fr in entries) + "\n")
        print(f"wrote {len(freqs)} entries -> {OUT} "
              f"(authored {n_auth}, mined new {n_mined})")
    return freqs


def load_dev():
    gold = []
    for line in open(DEV, encoding="utf-8"):
        line = line.strip()
        if line and not line.startswith("#"):
            gold.append(line.split())
    return gold


def tune():
    import itertools

    from deeplearning4j_tpu.nlp import cjk

    build(write=True)
    dev = load_dev()
    best = None
    for unk, unkc, pcost in itertools.product(
            (8.0, 10.0, 13.0, 16.0), (2.0, 3.5, 5.0), (1.0, 2.0, 3.5)):
        f = cjk.KoreanTokenizerFactory.__new__(cjk.KoreanTokenizerFactory)
        cjk.TokenizerFactory.__init__(f)
        f.split_particles = True
        f._engine = None
        f._mm = None
        f._morph = cjk._shared_ko_morph()
        if f._morph is not None:
            f._morph = f._morph.clone()
            f._morph.unk_stem_first = unk
            f._morph.unk_stem_char = unkc
            f._morph.particle_cost = pcost
        sc = cjk.segmentation_scores(f, dev, sep=" ")
        row = (sc["f1"], unk, unkc, pcost)
        print(f"unk={unk} unkc={unkc} pcost={pcost} -> P {sc['precision']}"
              f" R {sc['recall']} F1 {sc['f1']}")
        if best is None or row > best:
            best = row
    print(f"BEST: F1={best[0]} unk_stem_first={best[1]} "
          f"unk_stem_char={best[2]} particle_cost={best[3]}")


if __name__ == "__main__":
    if "--tune" in sys.argv:
        tune()
    else:
        build(write=True)
