#!/usr/bin/env python
"""Trainer.fit() vs raw step loop on ResNet-50 — validates that the
streaming fit loop (deferred loss readback, async prefetch) matches the
raw-loop throughput bench.py measures (VERDICT r1 'what's weak' #2)."""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

from deeplearning4j_tpu.data import BenchmarkIterator
from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.train import Trainer

BATCH = int(os.environ.get("FIT_BATCH", 128))
STEPS = int(os.environ.get("FIT_STEPS", 30))
IMG = int(os.environ.get("FIT_IMG", 224))


def main():
    zm = ResNet50(num_classes=1000, seed=0, input_shape=(IMG, IMG, 3))
    model = zm.build()
    if jax.devices()[0].platform != "cpu":
        model.config.compute_dtype = "bfloat16"
    model.init()
    tr = Trainer(model)

    # raw loop (bench.py's measurement): same batch, chained steps
    step = tr._make_step()
    ds = next(iter(BenchmarkIterator((IMG, IMG, 3), 1000, BATCH, 1)))
    x = jax.device_put(np.asarray(ds.features))
    y = jax.device_put(np.asarray(ds.labels))
    rng = jax.random.PRNGKey(0)
    p, o, s = tr.params, tr.opt_state, tr.state
    p, o, s, loss = step(p, o, s, x, y, rng)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        p, o, s, loss = step(p, o, s, x, y, rng)
    float(loss)
    raw = BATCH * STEPS / (time.perf_counter() - t0)

    # Trainer.fit on the same synthetic iterator. Re-init first: the raw
    # loop's donated step consumed model.params' buffers — a Trainer built
    # on them would crash with "Array has been deleted".
    model.init()
    tr = Trainer(model)
    tr.fit(BenchmarkIterator((IMG, IMG, 3), 1000, BATCH, 2), epochs=1)  # warm
    it = BenchmarkIterator((IMG, IMG, 3), 1000, BATCH, STEPS)
    t0 = time.perf_counter()
    tr.fit(it, epochs=1)
    fit = BATCH * STEPS / (time.perf_counter() - t0)

    print(f"raw loop: {raw:8.1f} img/s   Trainer.fit: {fit:8.1f} img/s   "
          f"ratio {fit / raw:.3f}")


if __name__ == "__main__":
    main()
