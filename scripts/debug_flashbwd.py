#!/usr/bin/env python
"""Bisect the Mosaic flash-backward wrong-gradients bug on chip.

Stage A: single grid block (nq=nk=1), non-causal — isolates one kernel
invocation (no scratch accumulation, no masking).
Stage B: a copy kernel that loads a (1, bq, 1) block and broadcasts it to
(bq, D) — isolates the 1-lane load path the backward uses for lse/delta.
Stage C: multi-block non-causal, then causal — isolates accumulation and
the mask/reachability specialization.
"""
import sys
import threading

sys.path.insert(0, "/root/repo")

out = {}
def probe():
    import jax
    out["d"] = jax.devices()
t = threading.Thread(target=probe, daemon=True)
t.start(); t.join(90)
if "d" not in out:
    print("WEDGED"); raise SystemExit(3)
print("devices:", out["d"])

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import deeplearning4j_tpu.ops.flash_attention as fa

rng = np.random.RandomState(0)


def grads(backend, q, k, v, causal, bq, bk):
    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(
            q, k, v, causal=causal, backward=backend,
            block_q=bq, block_k=bk) ** 2)
    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)


def cmp(tag, B, T, H, D, causal, bq, bk):
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))
    gx = grads("xla", q, k, v, causal, bq, bk)
    gp = grads("pallas", q, k, v, causal, bq, bk)
    for name, a, b in zip("qkv", gx, gp):
        err = float(jnp.max(jnp.abs(a - b)) /
                    (jnp.max(jnp.abs(a)) + 1e-30))
        print(f"{tag} d{name}: rel-max-err {err:.2e}", flush=True)


# Stage B first (cheapest signal): 1-lane block load + broadcast
def copy_kernel(x_ref, o_ref):
    o_ref[0] = jnp.broadcast_to(x_ref[0], o_ref.shape[1:])

bq, D = 256, 128
x = jnp.asarray(rng.randn(1, 512, 1), jnp.float32)
y = pl.pallas_call(
    copy_kernel,
    grid=(1, 2),
    in_specs=[pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0))],
    out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
    out_shape=jax.ShapeDtypeStruct((1, 512, D), jnp.float32),
)(x)
err = float(jnp.max(jnp.abs(y - jnp.broadcast_to(x, y.shape))))
print(f"stageB 1-lane load+broadcast: max-abs-err {err:.2e}", flush=True)

# Stage A: single block, non-causal
cmp("stageA single-block noncausal", 1, 256, 1, 128, False, 256, 256)
# Stage C1: multi-block non-causal (accumulation across k blocks)
cmp("stageC1 4-block noncausal", 1, 1024, 1, 128, False, 256, 256)
# Stage C2: multi-block causal (mask + reachability specialization)
cmp("stageC2 4-block causal", 1, 1024, 1, 128, True, 256, 256)
# Stage C3: the failing shape from chip_flashbwd
cmp("stageC3 orig", 2, 1024, 4, 64, True, 512, 512)
print("DONE", flush=True)
