#!/usr/bin/env python
"""Microbench: BN-backward-style reductions — XLA fusion vs Pallas kernel.

The ResNet-50 step spends ~10.6ms in multiply_reduce fusions (sum(dy),
sum(dy*x) + dx elementwise over (B,H,W,C)). This measures, on a
stage-1-sized tensor, whether a hand-written Pallas kernel beats XLA's
fusion throughput enough to justify a custom BN VJP.

Timing: iterations are chained (dx feeds the next dy) inside one jitted
fori_loop, so device time per iteration is (t(K2)-t(K1))/(K2-K1) with a
single data-dependent readback — robust over the axon tunnel.
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

M, C = 128 * 56 * 56, 256  # stage-1 shape flattened


def bn_bwd_xla(x, dy, a):
    s_dy = jnp.sum(dy, axis=0, dtype=jnp.float32)
    s_dyx = jnp.sum((dy * x).astype(jnp.float32), axis=0)
    dx = dy * a + (s_dy * (1.0 / M)).astype(x.dtype) + x * (s_dyx * (2.0 / M)).astype(x.dtype)
    return dx


def bn_bwd_pallas(x, dy, a):
    from jax.experimental import pallas as pl

    TM = 8192
    grid = M // TM

    def sum_kernel(x_ref, dy_ref, sdy_ref, sdyx_ref):
        i = pl.program_id(0)
        xv = x_ref[...].astype(jnp.float32)
        dyv = dy_ref[...].astype(jnp.float32)

        @pl.when(i == 0)
        def _():
            sdy_ref[...] = jnp.zeros_like(sdy_ref)
            sdyx_ref[...] = jnp.zeros_like(sdyx_ref)

        sdy_ref[...] += jnp.sum(dyv, axis=0, keepdims=True)
        sdyx_ref[...] += jnp.sum(dyv * xv, axis=0, keepdims=True)

    s_dy, s_dyx = pl.pallas_call(
        sum_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((TM, C), lambda i: (i, 0)),
                  pl.BlockSpec((TM, C), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, C), lambda i: (0, 0)),
                   pl.BlockSpec((1, C), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, C), jnp.float32),
                   jax.ShapeDtypeStruct((1, C), jnp.float32)],
    )(x, dy)

    c1 = (s_dy * (1.0 / M)).astype(x.dtype)
    c2 = (s_dyx * (2.0 / M)).astype(x.dtype)

    def dx_kernel(x_ref, dy_ref, a_ref, c1_ref, c2_ref, dx_ref):
        dx_ref[...] = dy_ref[...] * a_ref[...] + c1_ref[...] + x_ref[...] * c2_ref[...]

    dx = pl.pallas_call(
        dx_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((TM, C), lambda i: (i, 0)),
                  pl.BlockSpec((TM, C), lambda i: (i, 0)),
                  pl.BlockSpec((1, C), lambda i: (0, 0)),
                  pl.BlockSpec((1, C), lambda i: (0, 0)),
                  pl.BlockSpec((1, C), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((TM, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, C), x.dtype),
    )(x, dy, a.reshape(1, C), c1, c2)
    return dx


def make_loop(fn, k):
    @jax.jit
    def loop(x, dy, a):
        def body(_, dyc):
            return fn(x, dyc, a)

        return jax.lax.fori_loop(0, k, body, dy)

    return loop


def measure(fn, x, dy, a, k1=4, k2=24):
    l1, l2 = make_loop(fn, k1), make_loop(fn, k2)
    float(jnp.sum(l1(x, dy, a)[0]))  # compile+warm
    float(jnp.sum(l2(x, dy, a)[0]))
    t0 = time.perf_counter()
    float(jnp.sum(l1(x, dy, a)[0]))
    t1 = time.perf_counter()
    float(jnp.sum(l2(x, dy, a)[0]))
    t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (k2 - k1)


def main():
    rng = np.random.RandomState(0)
    x = jax.device_put(rng.randn(M, C).astype(np.float32).astype(jnp.bfloat16))
    dy = jax.device_put(rng.randn(M, C).astype(np.float32).astype(jnp.bfloat16))
    a = jax.device_put(rng.randn(C).astype(np.float32).astype(jnp.bfloat16))

    bytes_moved = (2 * M * C * 2) * 2 + M * C * 2  # read x,dy twice + write dx
    t = measure(bn_bwd_xla, x, dy, a)
    print(f"xla    {t * 1e3:7.3f} ms   {bytes_moved / t / 1e9:7.1f} GB/s effective")

    try:
        r0 = bn_bwd_xla(x, dy, a)
        r1 = bn_bwd_pallas(x, dy, a)
        np.testing.assert_allclose(np.asarray(r0, np.float32), np.asarray(r1, np.float32),
                                   rtol=5e-2, atol=5e-1)
        t = measure(bn_bwd_pallas, x, dy, a)
        print(f"pallas {t * 1e3:7.3f} ms   {bytes_moved / t / 1e9:7.1f} GB/s effective")
    except Exception as e:
        print(f"pallas failed: {type(e).__name__}: {str(e)[:400]}")


if __name__ == "__main__":
    main()
