#!/usr/bin/env python
"""CI smoke autoscale: one seeded drill through the whole elastic loop —
floor repair, scale-out under SLO burn, drain-based scale-in with
requests in flight, and dead-replica reap + same-tick repair. The
ISSUE-12 acceptance surface.

The drill (deterministic, seeded, CPU-only; membership leases, the SLO
burn window, AND the autoscaler's signal/cooldown clocks all run on one
skewable clock, so nothing ever waits on wall time):

- **0. floor repair** — the drill boots ONE replica under a policy floor
  of two: the first tick spawns a second replica via ``below_min``,
  which bypasses cooldown (a capacity floor is a hard constraint).
- **A. reference pass** — gold predict + generate answers become the
  ground truth; every later response must match bit-for-bit or be a
  typed error (zero wrong-params tolerance).
- **B. scale-out under burn** — a scoped chaos partition takes BOTH
  replicas off the air; gold traffic sheds typed, the 1m gold burn
  spikes above 1.0, and once the burn has *sustained* past the policy
  window the controller scales out. The first provision attempt is
  chaos-failed at the ``autoscale.spawn`` seam (counted, no cooldown
  burned) and the retry on the next tick succeeds: the newcomer
  AOT-warms from the shared store, beats into membership, placement
  re-plans, and gold traffic serves again THROUGH the partition (the
  newcomer is the only reachable replica). Aging the 1m window brings
  ``fleet_slo_burn_rate{slo_class="gold",window="1m"}`` back below 1.0.
- **C. idle scale-in drains first** — with the fleet idle and generates
  IN FLIGHT through the router, the controller picks the emptiest
  replica, removes it from membership (no new traffic), drains its
  models over ``/v1/admin/drain`` lease discipline, then stops it. Every
  in-flight generate completes token-identical to the reference: zero
  dropped, zero wrong-params. The retired replica's
  ``cluster_replica_state`` gauge series is DELETED — no ghost scrapes.
- **D. kill under load, reap + repair on one tick** — a replica is
  crash-killed under mixed traffic (every response typed or correct),
  its lease ages out, and a single tick reaps the corpse AND repairs the
  floor breach (``below_min`` again) — the fleet is back at two with no
  ghost series for the dead replica.

- **E. forecast-driven pre-spawn** — a standalone fake-clock drill over
  a 3-day sim workload with a known diurnal ramp: two identical policies
  watch the same burn curve, one additionally fed Holt-Winters burn
  forecasts from the telemetry store. The forecast policy must scale out
  at least one tick BEFORE the reactive burn-threshold policy, and its
  decision log must be byte-identical across two independent runs — the
  ISSUE-14 predictive-autoscale acceptance surface.

Artifacts: $CI_ARTIFACTS_DIR/smoke_autoscale_metrics.prom (+ _om.prom,
both validated by obs.promcheck), smoke_autoscale_decisions.jsonl (the
controller's canonical decision log), smoke_autoscale_forecast.jsonl
(the forecast-enabled demo decision log), and a flight_NN.json dump.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

SUSPECT_AFTER_S = 2.0
DEAD_AFTER_S = 45.0        # generous: spawns take real seconds mid-drill
X = [[0.1, -0.2, 0.3, -0.4]]
GEN_BODY = {"prompt": [3, 1, 4, 1, 5], "max_new_tokens": 6,
            "temperature": 0.0, "stream": False}

# one skewable clock for membership leases, the burn wheel, and the
# autoscaler's signals/cooldowns: bumping the skew ages all three in
# lockstep, so "sustained for 2s" and "1m window" never wait on wall time
CLOCK_SKEW = [0.0]


def _clock():
    return time.monotonic() + CLOCK_SKEW[0]


def _post(port, path, body, tenant=None):
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Tenant"] = tenant
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers=headers)
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read()), dict(r.headers)


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.status, r.read()


def _wait_ready(port, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            status, _ = _get(port, "/ready")
            if status == 200:
                return
        except (urllib.error.HTTPError, OSError):
            pass
        time.sleep(0.1)
    raise AssertionError(f"router not ready within {timeout_s}s")


def _metric(scrape: str, name: str, **labels) -> float:
    total = 0.0
    found = False
    for line in scrape.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in "{ ":
            continue  # a longer metric name sharing this prefix
        if not all(f'{k}="{v}"' in rest for k, v in labels.items()):
            continue
        total += float(line.rsplit(" ", 1)[1])
        found = True
    assert found, f"metric {name}{labels or ''} missing from scrape"
    return total


def _state_series(scrape: str) -> set:
    """Replica ids that still own a ``cluster_replica_state`` series."""
    out = set()
    for line in scrape.splitlines():
        if line.startswith("cluster_replica_state{"):
            label = line[len("cluster_replica_state{"):].split("}")[0]
            for item in label.split(","):
                k, _, v = item.partition("=")
                if k == "replica":
                    out.add(v.strip('"'))
    return out


def _typed_error(port, path, body, tenant=None):
    """POST expecting a typed error; returns (code, cause)."""
    try:
        _post(port, path, body, tenant=tenant)
    except urllib.error.HTTPError as e:
        payload = json.loads(e.read())
        assert "cause" in payload, f"untyped {e.code} from {path}: {payload}"
        return e.code, payload["cause"]
    raise AssertionError(f"{path} unexpectedly succeeded")


def _tick(ctl, step_s=1.0):
    """One control turn, one second later on the drill clock."""
    CLOCK_SKEW[0] += step_s
    return ctl.tick()


def forecast_demo(artifacts):
    """Phase E: predictive pre-spawn beats reactive scale-out on a ramp.

    Everything runs on an explicit fake clock against a stubbed signal
    surface — no sockets, no threads — so the decision stream is a pure
    function of (workload seed, policy knobs) and byte-identity across
    runs is a hard assertion, not a hope. The burn curve is the sim
    workload's own diurnal rate over a fixed capacity, the exact shape
    the ROADMAP's "predictive scale-out from the sim's diurnal
    fingerprints" names.
    """
    from deeplearning4j_tpu.autoscale import AutoscalePolicy
    from deeplearning4j_tpu.autoscale.signals import SignalReader
    from deeplearning4j_tpu.obs.forecast import BurnForecaster
    from deeplearning4j_tpu.obs.metrics import MetricsRegistry
    from deeplearning4j_tpu.obs.tsdb import TimeSeriesStore
    from deeplearning4j_tpu.sim import WorkloadSpec

    day_s = 240.0
    step_s = 2.0
    capacity_rps = 8.0  # peak offered rate is 11.4 rps: breaches mid-ramp
    spec = WorkloadSpec(seed=7, duration_s=day_s, days=3,
                        base_rate_rps=6.0, diurnal_amplitude=0.9,
                        diurnal_period_s=day_s, diurnal_phase=-0.25)

    def burn_at(t):
        return spec.rate_at(t % spec.total_duration_s) / capacity_rps

    class _CurveSlo:
        """SloBurn-snapshot-shaped view of the diurnal burn curve."""

        def __init__(self):
            self.burn = 0.0

        def snapshot(self):
            return {"m": {"gold": {"good": 0, "bad": 0, "target": 0.999,
                                   "burn": {"1m": self.burn,
                                            "10m": self.burn}}}}

    class _OneReplica:
        """Membership-read-shaped stub: one healthy, empty replica."""

        @staticmethod
        def ids():
            return ["sim-0"]

        @staticmethod
        def state(rid):
            return "alive"

        @staticmethod
        def payload(rid):
            return {"queue_depth": 0, "kv_utilization": 0.0}

    def run(with_forecast):
        """Replay days 1-2 into the store, then decide through day 3's
        ramp; returns the day-3 decision list."""
        t_box = [0.0]
        clock = lambda: t_box[0]  # noqa: E731 — the drill's fake clock
        reg = MetricsRegistry()
        store = TimeSeriesStore(clock=clock)
        forecaster = BurnForecaster(store, season_s=day_s,
                                    horizon_s=3 * step_s)
        slo = _CurveSlo()
        reader = SignalReader(slo=slo, membership=_OneReplica(),
                              clock=clock)
        policy = AutoscalePolicy(
            min_replicas=1, max_replicas=4, burn_out={"gold": 1.0},
            sustain_out_s=step_s, sustain_in_s=1e9,
            cooldown_out_s=4 * step_s, cooldown_in_s=1e9,
            queue_high=1e9, queue_low=0.0, forecast_confidence=0.6)

        def observe(t):
            t_box[0] = t
            slo.burn = burn_at(t)
            reg.gauge("fleet_slo_burn_rate",
                      {"model": "m", "slo_class": "gold",
                       "window": "1m"}).set(slo.burn)
            store.ingest("router", reg.snapshot(), now=t)

        t = 0.0
        while t < 2 * day_s:  # two warm days teach the seasonal profile
            observe(t)
            t += step_s
        current = 1
        decisions = []
        while t < 2 * day_s + day_s / 2:  # day 3: trough -> peak ramp
            observe(t)
            reader.sample()
            forecast = None
            if with_forecast:
                forecast = {"gold": forecaster.forecast_burn("gold")}
            d = policy.decide(reader, current, t, forecast=forecast)
            decisions.append(d)
            if d.direction == "out" and d.amount:
                current += d.amount
                policy.commit(d, t)
            t += step_s
        return decisions

    print("=== phase E: forecast-driven pre-spawn on a diurnal ramp ===",
          flush=True)
    reactive = run(with_forecast=False)
    predictive = run(with_forecast=True)
    # byte-identity: a second independent run must reproduce the forecast
    # decision stream exactly (fixed seed + fake clock, 6-dp evidence)
    log = "\n".join(d.to_json() for d in predictive) + "\n"
    assert log == "\n".join(d.to_json()
                            for d in run(with_forecast=True)) + "\n", \
        "forecast decision log is not reproducible"
    with open(os.path.join(artifacts, "smoke_autoscale_forecast.jsonl"),
              "w") as f:
        f.write(log)

    def first_out(decisions):
        return next(i for i, d in enumerate(decisions)
                    if d.direction == "out")

    i_react = first_out(reactive)
    i_pred = first_out(predictive)
    assert predictive[i_pred].reason == "forecast", predictive[i_pred]
    assert i_pred < i_react, \
        f"forecast scaled at tick {i_pred}, reactive at {i_react}"
    # the reactive policy only moves once the live threshold actually
    # trips; the forecast acted while the observed burn was still < 1.0
    assert reactive[i_react].evidence["burn"]["gold"] >= 1.0
    assert predictive[i_pred].evidence["burn"]["gold"] < 1.0
    assert predictive[i_pred].evidence["forecast"]["gold"]["value"] >= 1.0
    assert predictive[i_pred].evidence["forecast"]["gold"][
        "confidence"] >= 0.6
    print(f"forecast pre-spawned at tick {i_pred}, reactive at {i_react} "
          f"({i_react - i_pred} ticks earlier)", flush=True)
    return i_react - i_pred


def main():
    artifacts = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(artifacts, exist_ok=True)

    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.autoscale import (AutoscaleController,
                                              AutoscalePolicy)
    from deeplearning4j_tpu.chaos import FaultPlane, install, uninstall
    from deeplearning4j_tpu.cluster import ClusterRouter, spawn_replica
    from deeplearning4j_tpu.fleet import FleetRegistry
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.nn.layers import Dense, Output
    from deeplearning4j_tpu.nn.model import NetConfig, Sequential
    from deeplearning4j_tpu.obs import flight as flight_mod
    from deeplearning4j_tpu.obs.flight import FlightRecorder
    from deeplearning4j_tpu.obs.promcheck import check_text

    recorder = flight_mod.install(FlightRecorder(out_dir=artifacts))

    store_dir = tempfile.mkdtemp(prefix="smoke_autoscale_aot_")
    handles = {}

    def factory(rid):
        """One replica: dense model + LM over the SHARED AOT store; seeds
        shared across replicas, so every replica computes the same
        answers — the drill's wrong-params oracle."""
        dense = Sequential(NetConfig(seed=0),
                           [Dense(n_out=6, activation="tanh"),
                            Output(n_out=3, loss="mcxent",
                                   activation="softmax")], (4,))
        dense.init()
        lm = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                      num_heads=4, vocab=50).build()
        lm.init()
        fleet = FleetRegistry(aot_store=AotStore(store_dir))
        fleet.add("d", dense)
        fleet.add("g", lm, input_dtype=np.int32,
                  gen_opts={"slots": 2, "capacity": 24, "seed": 0})
        handles[rid] = spawn_replica(rid, fleet)
        return handles[rid]

    # heartbeat parked at 1h: the controller's ticks drive every poll, so
    # membership, burn, and decisions advance only when the drill says so
    router = ClusterRouter(port=0, heartbeat_s=3600.0, hedge_ms=None,
                           suspect_after_s=SUSPECT_AFTER_S,
                           dead_after_s=DEAD_AFTER_S, clock=_clock)
    router.tenants.register("vip", rate_per_s=1000.0, slo="gold")
    router.tenants.register("std", rate_per_s=1000.0, slo="standard")
    seed = factory("r1")
    router.add_replica("r1", seed.base_url)
    router.start()
    port = router.port

    policy = AutoscalePolicy(min_replicas=2, max_replicas=3,
                             sustain_out_s=1.5, sustain_in_s=2.0,
                             cooldown_out_s=4.0, cooldown_in_s=4.0,
                             queue_high=1e9, queue_low=10.0)
    ctl = AutoscaleController(router, factory, policy=policy,
                              clock=_clock, beat_wait_s=2.0)
    ctl.adopt("r1", seed)
    try:
        _wait_ready(port)

        # ---- 0: one replica under a floor of two -> immediate repair
        print("=== phase 0: below_min floor repair ===", flush=True)
        d = _tick(ctl)
        assert (d.direction, d.reason) == ("out", "below_min"), d
        assert sorted(handles) == ["as-0", "r1"]
        assert router.membership.state("as-0") == "alive"

        # ---- A: fault-free reference pass
        print("=== phase A: reference pass ===", flush=True)
        ref_pred = _post(port, "/v1/models/d/predict", {"ndarray": X},
                         tenant="vip")[0]
        ref_toks = _post(port, "/v1/models/g/generate?stream=false",
                         GEN_BODY, tenant="std")[0]["tokens"]
        assert ref_toks, "reference generation returned no tokens"

        # ---- B: partition both replicas -> burn spike -> scale out
        print("=== phase B: scale-out under sustained gold burn ===",
              flush=True)
        fp = install(FaultPlane(seed=0, metrics=router.metrics))
        for rid in ("r1", "as-0"):
            fp.inject_spec(
                f"cluster.transport:error:type=connection,scope={rid},"
                f"times=-1")
        # the FIRST provision attempt fails at the chaos seam — the
        # controller must count it, burn no cooldown, and retry
        fp.inject_spec("autoscale.spawn:error:type=runtime,times=1")

        decisions = []
        for _ in range(5):
            if ctl.replica_stats()["final"] >= 3:
                break
            for _ in range(3):
                code, cause = _typed_error(
                    port, "/v1/models/d/predict", {"ndarray": X},
                    tenant="vip")
                assert code in (502, 503) and cause in (
                    "upstream_unreachable", "no_replica"), (code, cause)
            scrape = _get(port, "/metrics")[1].decode()
            burn = _metric(scrape, "fleet_slo_burn_rate", model="d",
                           slo_class="gold", window="1m")
            assert burn > 1.0, f"gold burn did not spike: {burn}"
            decisions.append(_tick(ctl))
        reasons = [(d.direction, d.reason) for d in decisions]
        assert ("out", "burn") in reasons, reasons
        assert ctl.replica_stats()["final"] == 3, reasons
        # the failed attempt must not consume an id: the retry IS "as-1"
        assert "as-1" in handles and "as-2" not in handles, sorted(handles)
        scrape = _get(port, "/metrics")[1].decode()
        assert _metric(scrape, "autoscale_spawn_failures_total") == 1

        # elastic capacity arrived: the newcomer is the ONLY reachable
        # replica, and gold traffic serves through the partition
        out = _post(port, "/v1/models/d/predict", {"ndarray": X},
                    tenant="vip")[0]
        assert np.allclose(out["output"], ref_pred["output"]), \
            "newcomer served wrong params"
        uninstall()
        # age the bad events out of the 1m gold window and serve traffic:
        # burn must recover below 1.0 — the ROADMAP drill's exit criterion
        CLOCK_SKEW[0] += 61.0
        router.poll_once()  # resurrect the healed replicas (no decision)
        for _ in range(5):
            out = _post(port, "/v1/models/d/predict", {"ndarray": X},
                        tenant="vip")[0]
            assert np.allclose(out["output"], ref_pred["output"])
        scrape = _get(port, "/metrics")[1].decode()
        burn = _metric(scrape, "fleet_slo_burn_rate", model="d",
                       slo_class="gold", window="1m")
        assert burn < 1.0, f"gold burn did not recover: {burn}"

        # ---- C: idle scale-in drains before retiring, in-flight survives
        print("=== phase C: drain-based scale-in with requests in flight ===",
              flush=True)
        results, errors = [], []

        def fire():
            try:
                results.append(_post(
                    port, "/v1/models/g/generate?stream=false", GEN_BODY,
                    tenant="std")[0]["tokens"])
            except Exception as e:  # any failure fails the drill below  # jaxlint: disable=broad-except
                errors.append(e)

        before = set(router.membership.ids())
        for _ in range(4):
            if ctl.replica_stats()["final"] <= 2:
                break
            threads = [threading.Thread(target=fire) for _ in range(3)]
            for t in threads:
                t.start()
            d = _tick(ctl)
            for t in threads:
                t.join(timeout=60)
        assert ctl.replica_stats()["final"] == 2, d
        assert not errors, f"requests dropped during scale-in: {errors}"
        assert results and all(r == ref_toks for r in results), \
            "wrong params served during drain-then-retire"
        retired = before - set(router.membership.ids())
        assert len(retired) == 1, retired
        victim = retired.pop()
        assert not handles[victim].alive(), "victim still running"
        scrape = _get(port, "/metrics")[1].decode()
        assert victim not in _state_series(scrape), \
            f"retired {victim} left a ghost cluster_replica_state series"
        assert _metric(scrape, "autoscale_retired_total",
                       cause="scale_in") == 1
        # the lease-drain handshake itself must succeed — a 400 here means
        # stop() is silently doing all the draining (regression: the drain
        # handler once called the .resident property as a method)
        assert _metric(scrape, "autoscale_drains_total", outcome="ok") >= 1
        assert "autoscale_drains_total{outcome=\"error\"}" not in scrape, \
            "some /v1/admin/drain calls failed"

        # ---- D: crash-kill under load -> reap + floor repair on one tick
        print("=== phase D: kill, reap, same-tick repair ===", flush=True)
        alive = sorted(set(router.membership.ids()))
        dead_rid = next(r for r in alive if r != "r1")
        handles[dead_rid].kill()
        for _ in range(6):  # mixed load across the kill: typed or correct
            try:
                out = _post(port, "/v1/models/d/predict", {"ndarray": X},
                            tenant="vip")[0]
            except urllib.error.HTTPError as e:
                payload = json.loads(e.read())
                assert e.code != 500 and "cause" in payload, \
                    f"raw/untyped error {e.code}: {payload}"
            else:
                assert np.allclose(out["output"], ref_pred["output"]), \
                    "WRONG-PARAMS answer during the kill window"
        CLOCK_SKEW[0] += DEAD_AFTER_S  # age the corpse's lease out
        d = ctl.tick()
        assert (d.direction, d.reason) == ("out", "below_min"), d
        assert dead_rid not in router.membership.ids()
        assert ctl.replica_stats()["final"] == 2
        toks = _post(port, "/v1/models/g/generate?stream=false", GEN_BODY,
                     tenant="std")[0]["tokens"]
        assert toks == ref_toks, "repaired fleet diverged from reference"

        # ---- final: metrics moved, no ghosts, expositions valid
        scrape = _get(port, "/metrics")[1].decode()
        with open(os.path.join(artifacts, "smoke_autoscale_metrics.prom"),
                  "w") as f:
            f.write(scrape)
        assert _metric(scrape, "autoscale_replicas_actual") == 2
        assert _metric(scrape, "autoscale_replicas_desired") == 2
        assert _metric(scrape, "autoscale_decisions_total",
                       direction="out") >= 3
        assert _metric(scrape, "autoscale_decisions_total",
                       direction="in", reason="idle") >= 1
        assert _metric(scrape, "autoscale_scale_seconds_count",
                       direction="out") >= 2
        assert _metric(scrape, "autoscale_scale_seconds_count",
                       direction="in") >= 1
        assert _metric(scrape, "autoscale_retired_total", cause="dead") == 1
        # the scrape shows EXACTLY the live fleet — retired and dead
        # replicas own no state series
        assert _state_series(scrape) == set(router.membership.ids())
        errs = check_text(scrape, openmetrics=False)
        assert not errs, f"invalid /metrics exposition: {errs[:5]}"
        om = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text"}),
            timeout=30).read().decode()
        with open(os.path.join(artifacts,
                               "smoke_autoscale_metrics_om.prom"), "w") as f:
            f.write(om)
        errs = check_text(om)
        assert not errs, f"invalid OpenMetrics exposition: {errs[:5]}"

        # the autoscaler is observable on the cluster surface
        view = json.loads(_get(port, "/v1/cluster")[1])
        assert view["autoscale"]["actual"] == 2
        assert view["autoscale"]["policy"]["min_replicas"] == 2
        assert view["autoscale"]["last_decision"] is not None

        # canonical decision log -> artifact (the byte-identity surface)
        log_bytes = ctl.decision_log_bytes()
        with open(os.path.join(artifacts, "smoke_autoscale_decisions.jsonl"),
                  "wb") as f:
            f.write(log_bytes)
        lines = [json.loads(ln) for ln in log_bytes.decode().splitlines()]
        assert len(lines) == ctl.snapshot()["ticks"]
        assert all("decision" in ln and "evidence" in ln["decision"]
                   for ln in lines)

        dump_path = recorder.dump("autoscale_drill")
        assert dump_path is not None, "flight recorder refused to dump"
        with open(dump_path) as f:
            dumped = json.load(f)
        kinds = {(e.get("kind"), e.get("name"))
                 for e in dumped.get("events", [])}
        for what in ("spawned", "retired", "reaped"):
            assert ("autoscale", what) in kinds, \
                f"flight recorder missing autoscale/{what}: {sorted(kinds)}"
    finally:
        uninstall()
        ctl.stop()
        router.stop()
        for h in handles.values():
            if h.alive():
                h.stop()
        flight_mod.uninstall()

    # nothing left running: router, controller, replicas, batchers all down
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        hung = [t for t in threading.enumerate()
                if t.name.startswith(("serve-", "fleet-", "cluster-",
                                      "autoscale-"))
                and t.is_alive()]
        if not hung:
            break
        time.sleep(0.1)
    assert not hung, f"threads left hanging: {[t.name for t in hung]}"

    lead = forecast_demo(artifacts)
    print("smoke autoscale OK: floor repaired, scaled out under burn, "
          "burn recovered < 1.0, drain-based scale-in dropped nothing, "
          "dead replica reaped with no ghost series, forecast pre-spawned "
          f"{lead} tick(s) ahead of the reactive policy")


if __name__ == "__main__":
    main()
