#!/usr/bin/env python
"""Transformer training MFU on the real chip — the matmul-bound counterpart
to the ResNet-50 bench (PERF.md): a GPT-style causal LM train step, flash vs
dense attention, sparse-label LM loss, MFU from 6*N*tokens + attention FLOPs.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

PEAK = 197e12  # v5e bf16

B = int(os.environ.get("TB_BATCH", 8))
T = int(os.environ.get("TB_SEQ", 2048))
L = int(os.environ.get("TB_LAYERS", 12))
DM = int(os.environ.get("TB_DMODEL", 768))
V = int(os.environ.get("TB_VOCAB", 32000))


def measure(flash):
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.train import Trainer

    zm = CausalLM(seed=0, input_shape=(T,), num_layers=L, d_model=DM,
                  num_heads=DM // 64, vocab=V, flash=flash)
    m = zm.build()
    m.config.compute_dtype = "bfloat16"
    m.init()
    tr = Trainer(m)
    step = tr._make_step()
    rng = np.random.RandomState(0)
    x = jax.device_put(rng.randint(0, V, (B, T)).astype(np.int32))
    y = jax.device_put(rng.randint(0, V, (B, T)).astype(np.int32))
    r = jax.random.PRNGKey(0)
    p, o, s = tr.params, tr.opt_state, tr.state
    p, o, s, loss = step(p, o, s, x, y, r)
    lf = float(loss)

    def run(k, p, o, s):
        t0 = time.perf_counter()
        for _ in range(k):
            p, o, s, loss = step(p, o, s, x, y, r)
        float(loss)
        return time.perf_counter() - t0, p, o, s

    t1, p, o, s = run(3, p, o, s)
    t2, p, o, s = run(12, p, o, s)
    dt = (t2 - t1) / 9
    n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(tr.params))
    # 6ND counts only MATMUL parameters: token/positional embedding tables
    # are gathers (their fwd is O(B*T*D) lookups, not 2*N*B*T flops) — the
    # LM head matmul is real and stays. Counting embeddings inflates MFU
    # ~19% at V=32k d=768.
    from deeplearning4j_tpu.nn.layers import EmbeddingSequence, PositionalEmbedding
    from deeplearning4j_tpu.nn.model import _layer_key

    n_embed = sum(
        int(np.prod(a.shape))
        for i, layer in enumerate(m.layers)
        if isinstance(layer, (EmbeddingSequence, PositionalEmbedding))
        for a in jax.tree.leaves(tr.params.get(_layer_key(i, layer), {})))
    n_matmul = n_params - n_embed
    # + causal attention: 12*B*T^2*DM*L/2 (fwd+bwd, halved for causality)
    flops = 6 * n_matmul * B * T + 12 * B * T * T * DM * L // 2
    return dt, flops / dt / PEAK, lf, n_params, n_matmul


def main():
    for flash in (False, True):
        try:
            dt, mfu, loss, n, nm = measure(flash)
            print(f"flash={flash}: {dt * 1e3:8.2f} ms/step  MFU {mfu:.3f}  "
                  f"loss {loss:.3f}  params {n / 1e6:.1f}M "
                  f"(matmul {nm / 1e6:.1f}M)  tokens/s {B * T / dt:,.0f}")
        except Exception as e:
            print(f"flash={flash} failed: {type(e).__name__}: {str(e)[:300]}")


if __name__ == "__main__":
    main()
