#!/usr/bin/env python
"""Long-context end-to-end training on chip: CausalLM full train steps at
T=16k and T=64k (flash attention + per-block remat), tokens/s + MFU.

The reference's long-sequence ceiling is tBPTT windowing
(MultiLayerNetwork.java:1309-1311) — it cannot take a true gradient over a
64k context at all. These rows measure our framework doing exactly that on
one v5e chip. Vocab is 8k for the 64k row so the (T, V) logits stay inside
HBM; MFU is computed from compiled cost_analysis flops either way.
"""
import json
import sys

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

from chiputil import smoke_or_probe

SMOKE = smoke_or_probe()  # CPU shakeout: same code path (flash + remat +
#                           rope + window), toy sizes

import model_benches as mb

# seq stays tiny: the Pallas kernel runs INTERPRETED on CPU, so every
# extra block costs minutes, and the point is signatures, not speed
SMOKE_JOBS = [
    ("smoke_full", dict(num_layers=1, d_model=32, batch=1, seq=128,
                        vocab=64, flash=True, remat=True, pos="rope",
                        steps=1)),
    ("smoke_window", dict(num_layers=1, d_model=32, batch=1, seq=128,
                          vocab=64, flash=True, remat=True, pos="rope",
                          window=64, steps=1)),
]
JOBS = SMOKE_JOBS if SMOKE else [
    # 12-layer d=1536 (the 440M family): T=16k, batch 2. pos="rope": no
    # learned table (100M params at T=64k) — the long-context design.
    ("longctx_t16k", dict(num_layers=12, d_model=1536, batch=2, seq=16384,
                          vocab=8192, flash=True, remat=True, pos="rope",
                          steps=6)),
    # T=64k, batch 1 — the headline long-context row
    ("longctx_t64k", dict(num_layers=12, d_model=1536, batch=1, seq=65536,
                          vocab=8192, flash=True, remat=True, pos="rope",
                          steps=3)),
]

# sliding-window variant: window=4096 cuts attention work ~16x at T=64k —
# the local-attention throughput row (tokens/s comparison vs full causal).
# (smoke mode has its own window job; the full-size one must NOT leak in)
if not SMOKE:
    JOBS.append(("longctx_t64k_w4k", dict(num_layers=12, d_model=1536,
                                          batch=1, seq=65536, vocab=8192,
                                          flash=True, remat=True, pos="rope",
                                          window=4096, steps=3)))

results = {}
for name, kw in JOBS:
    try:
        r = mb.bench_transformer(**kw)
        r["remat"] = True
        results[name] = r
        print(name, json.dumps(r), flush=True)
    except Exception as e:
        results[name] = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
        print(name, "ERROR", results[name]["error"], flush=True)

with open("/tmp/chip_longctx_results.json", "w") as f:
    json.dump(results, f, indent=1)
print("DONE -> /tmp/chip_longctx_results.json")
