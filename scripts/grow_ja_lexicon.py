#!/usr/bin/env python
"""Build the Japanese frequency lexicon for the unigram-Viterbi segmenter
(r4 VERDICT #4: grow ja from 1.3k words / F1 0.717 to a real dictionary).

Sources (all offline, provenance documented in PARITY.md):

1. CORPUS — the reference's ipadic-tokenized test corpora
   (deeplearning4j-nlp-japanese/src/test/resources/
   bocchan-ipadic-features.txt ~69.5k tokens of Natsume Soseki's public-
   domain novel "Botchan", + jawikisentences-ipadic-features.txt): real
   surface frequencies, especially the function-word distribution the
   unigram model lives on. Auxiliary chains are merged to this framework's
   segmentation convention (documented in tests/data/cjk_gold_ja.txt's
   header): まし+た→ました, でし+た→でした, なかっ+た→なかった, and
   adjective 連用タ接続+た → fused past (強かっ+た→強かった); verb stems
   stay split from た/て.
2. EXPANSION — deeplearning4j_tpu/nlp/ja_conjugation.expand() generates
   every conjugated surface for each (base, 活用型) pair seen in the
   corpus or tagged in the authored vocabulary (the ipadic-dictionary
   design: every inflected form is its own entry).
3. AUTHORED — nlp/data/ja_base_vocab.txt: knowledge-written general
   modern vocabulary (never tuned on the gold set).
4. MINED — Sino-Japanese kanji compounds from jieba's MIT-licensed
   dict.txt mapped through a simplified→shinjitai character table
   (经济→経済, 图书馆→図書館). Words containing characters without a
   confident mapping are dropped; survivors enter at a heavily discounted
   frequency so corpus/authored entries always dominate. Wrong survivors
   (Chinese-only compounds) are dead entries — they never appear in
   Japanese text, so they cost size, not accuracy.

Output: deeplearning4j_tpu/nlp/data/ja_lexicon.txt ("word freq" lines).

--tune: grid-search the unknown-word penalties of
JapaneseUnigramTokenizerFactory on a HELD-OUT slice of the Botchan corpus
(every 10th sentence, excluded from the frequency counts) — fully
independent of the hand-authored gold set in tests/data.
"""

import os
import sys
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

JA_RES = ("/root/reference/deeplearning4j-nlp-parent/"
          "deeplearning4j-nlp-japanese/src/test/resources")
CORPORA = ("bocchan-ipadic-features.txt", "jawikisentences-ipadic-features.txt")
OUT = os.path.join(REPO, "deeplearning4j_tpu", "nlp", "data", "ja_lexicon.txt")
VOCAB = os.path.join(REPO, "deeplearning4j_tpu", "nlp", "data",
                     "ja_base_vocab.txt")

# simplified -> Japanese (shinjitai) character map for mining jieba's
# dictionary. Only confident 1:1 mappings; anything else drops the word.
ZH2JA = {}
for pair in (
    "爱愛 贝貝 笔筆 边辺 变変 标標 别別 宾賓 补補 产産 长長 车車 诚誠 迟遅 "
    "齿歯 处処 传伝 创創 词詞 从従 达達 带帯 单単 导導 岛島 敌敵 电電 东東 "
    "动動 对対 队隊 顿頓 夺奪 恶悪 儿児 发発 饭飯 访訪 纷紛 凤鳳 负負 妇婦 "
    "复複 钢鋼 个個 给給 贡貢 观観 关関 广広 规規 贵貴 过過 汉漢 黑黒 红紅 "
    "后後 华華 话話 怀懐 欢歓 环環 还還 会会 货貨 机機 鸡鶏 积積 极極 级級 "
    "记記 际際 济済 继継 价価 间間 简簡 见見 键鍵 讲講 奖奨 阶階 节節 结結 "
    "进進 经経 惊驚 镜鏡 举挙 剧劇 决決 觉覚 军軍 开開 壳殻 课課 块塊 矿鉱 "
    "兰蘭 蓝藍 劳労 乐楽 类類 离離 历歴 丽麗 连連 联連 练練 凉涼 两両 铃鈴 "
    "龄齢 领領 龙竜 楼楼 绿緑 乱乱 论論 罗羅 马馬 买買 卖売 满満 贸貿 门門 "
    "梦夢 难難 脑脳 鸟鳥 农農 欧欧 盘盤 齐斉 气気 钱銭 浅浅 强強 桥橋 亲親 "
    "轻軽 请請 穷窮 区区 权権 确確 让譲 热熱 认認 荣栄 软軟 烧焼 设設 声声 "
    "胜勝 师師 诗詩 时時 实実 识識 视視 试試 收収 书書 术術 树樹 数数 双双 "
    "说説 丝糸 诉訴 岁歳 孙孫 态態 谈談 汤湯 题題 体体 条条 铁鉄 厅庁 听聴 "
    "头頭 图図 团団 万万 为為 围囲 维維 伟偉 卫衛 问問 无無 习習 细細 现現 "
    "线線 乡郷 响響 写写 兴興 压圧 亚亜 严厳 颜顔 阳陽 养養 样様 药薬 业業 "
    "叶葉 医医 艺芸 亿億 义義 议議 译訳 异異 银銀 饮飲 应応 营営 优優 邮郵 "
    "鱼魚 语語 员員 园園 远遠 愿願 约約 云雲 运運 杂雑 脏臓 则則 增増 张張 "
    "镇鎮 争争 证証 值値 职職 纸紙 制製 质質 种種 专専 转転 装装 状状 准準 "
    "资資 总総 组組 闻聞 闭閉 闲閑 阅閲 飞飛 阵陣 阴陰 陆陸 陈陳 湾湾 渐漸 "
    "灾災 炼錬 烟煙 犹猶 独独 狮獅 顶頂 顺順 须須 顾顧 预予 额額 验験 骑騎 "
    "鲜鮮 鸣鳴 称称 点点 当当 党党 灯灯 断断 号号 回回 旧旧 静静 来来 了了 "
    "楽楽 满満 面面 民民 明明 名名 命命 内内 能能 平平 品品 票票 普普 期期 "
    "汽汽 器器 前前 青青 清清 情情 秋秋 求求 取取 去去 全全 人人 任任 日日 "
    "肉肉 如如 三三 色色 山山 商商 上上 少少 社社 身身 深深 神神 生生 史史 "
    "使使 始始 世世 市市 事事 室室 手手 首首 思思 死死 四四 送送 所所 他他 "
    "台台 太太 天天 同同 土土 推推 外外 往往 望望 温温 文文 物物 西西 系系 "
    "下下 先先 限限 相相 想想 向向 象象 消消 小小 校校 笑笑 心心 新新 信信 "
    "星星 行行 形形 幸幸 性性 姓姓 学学 雪雪 研研 眼眼 要要 夜夜 一一 衣衣 "
    "易易 意意 因因 音音 英英 影影 映映 硬硬 用用 游遊 友友 有有 又又 右右 "
    "雨雨 院院 月月 越越 在在 早早 造造 照照 着着 真真 整整 正正 政政 知知 "
    "直直 植植 指指 中中 重重 州州 周周 洲洲 主主 住住 助助 注注 子子 字字 "
    "自自 走走 最最 昨昨 左左 作作 坐坐 座座 阿阿 安安 案案 八八 白白 百百 "
    "班班 半半 办弁 包包 保保 报報 北北 被被 本本 比比 必必 毕毕 便便 表表 "
    "兵兵 病病 波波 博博 不不 布布 步步 部部 才才 材材 菜菜 参参 草草 层層 "
    "查查 茶茶 差差 常常 场場 唱唱 朝朝 城城 成成 程程 吃吃 出出 初初 除除 "
    "船船 春春 次次 村村 错錯 大大 代代 待待 担担 但但 道道 得得 德徳 登登 "
    "等等 地地 第第 弟弟 典典 店店 调調 定定 丢丢 冬冬 都都 度度 短短 段段 "
    "多多 朵朵 二二 法法 反反 犯犯 房房 放放 非非 分分 份份 封封 夫夫 服服 "
    "福福 府府 父父 付付 改改 概概 干干 感感 刚剛 港港 格格 各各 根根 更更 "
    "公公 功功 共共 狗狗 古古 故故 固固 顾顧 瓜瓜 挂掛 怪怪 官官 管管 光光 "
    "好好 和和 合合 何何 河河 很很 恨恨 横横 红紅 湖湖 虎虎 互互 户戸 花花 "
    "化化 划划 坏壊 换換 黄黄 婚婚 活活 火火 或或 货貨 基基 急急 集集 几几 "
    "己己 技技 季季 既既 加加 假仮 监監 坚堅 件件 健健 江江 将将 交交 角角 "
    "脚脚 叫叫 教教 接接 街街 姐姐 介介 界界 今今 紧緊 近近 京京 精精 井井 "
    "警警 九九 酒酒 久久 就就 居居 局局 具具 句句 据拠 聚聚 卷巻 军軍 卡卡 "
    "看看 考考 靠靠 科科 可可 克克 客客 肯肯 空空 口口 苦苦 夸誇 款款 况況 "
    "亏虧 困困 扩拡 拉拉 来来 蓝藍 老老 累累 冷冷 里里 礼礼 力力 立立 利利 "
    "例例 俩俩 良良 料料 列列 林林 留留 流流 六六 陆陸 路路 旅旅 率率 律律 "
    "妈媽 麻麻 毛毛 冒冒 帽帽 每毎 美美 妹妹 米米 密密 蜜蜜 免免 妙妙 庙廟 "
    "灭滅 明明 模模 母母 木木 目目 拿拿 那那 奶奶 南南 男男 闹鬧 呢呢 泥泥 "
    "年年 念念 牛牛 浓濃 女女 怕怕 拍拍 排排 派派 盼盼 跑跑 陪陪 朋朋 皮皮 "
    "篇篇 偏偏 品品 破破 普普 妻妻 七七 起起 千千 签簽 钱銭 枪槍 墙墻 切切 "
    "且且 琴琴 轮輪 "
).split():
    if len(pair) == 2:
        ZH2JA[pair[0]] = pair[1]  # identity pairs mark chars SHARED
        #                           between simplified Chinese and
        #                           Japanese usage; differing pairs map
        #                           simplified -> shinjitai


def _is_han(w):
    return all(0x4E00 <= ord(c) <= 0x9FFF for c in w)


def _is_cjk_word(w):
    """All chars kana/han (lexicon-eligible for the ja segmenter)."""
    for c in w:
        o = ord(c)
        if not (0x3040 <= o <= 0x30FF or 0x4E00 <= o <= 0x9FFF
                or c == "ー" or c == "々"):
            return False
    return True


def parse_corpus(dev_every: int = 10):
    """Parse the ipadic features files into convention-merged sentences.
    Returns (train_sentences, dev_sentences); each sentence is a list of
    (surface, pos, conj_type, base). Sentences split at 。！？ tokens;
    every ``dev_every``-th Botchan sentence goes to dev."""
    train, dev = [], []
    for name in CORPORA:
        path = os.path.join(JA_RES, name)
        if not os.path.exists(path):
            continue
        sent, sents = [], []
        in_ruby = False  # Botchan is Aozora-formatted: 《reading》 ruby
        #                  annotations duplicate the preceding word's kana
        #                  reading — skip them so frequencies and the dev
        #                  gold reflect the actual text
        for line in open(path, encoding="utf-8"):
            line = line.rstrip("\n")
            if not line or "\t" not in line:
                continue
            surface, feat = line.split("\t", 1)
            p = feat.split(",")
            pos = p[0]
            conj_type = p[4] if len(p) > 4 else "*"
            conj_form = p[5] if len(p) > 5 else "*"
            base = p[6] if len(p) > 6 else "*"
            if pos == "記号":
                if "《" in surface:
                    in_ruby = True
                if "》" in surface:
                    in_ruby = False
                if surface in "。！？!?":
                    if sent:
                        sents.append(sent)
                        sent = []
                continue
            if in_ruby:
                continue
            # convention merges (see module docstring)
            if (pos == "助動詞" and surface in ("た", "だ") and sent):
                ps, ppos, pconj, pform, _pb = sent[-1]
                if (ppos == "助動詞" and
                        (ps in ("まし", "でし", "なかっ", "だっ", "かっ")
                         or pform == "連用タ接続")) or \
                   (ppos == "形容詞" and pform == "連用タ接続"):
                    sent[-1] = (ps + surface, ppos, pconj, "*", "*")
                    continue
            sent.append((surface, pos, conj_type, conj_form, base))
        if sent:
            sents.append(sent)
        if name.startswith("bocchan"):
            for i, s in enumerate(sents):
                (dev if i % dev_every == 0 else train).append(s)
        else:
            train.extend(sents)
    return train, dev


def build(write=True, dev_every=10):
    from deeplearning4j_tpu.nlp.ja_conjugation import expand

    train, dev = parse_corpus(dev_every)
    freqs = Counter()
    lexemes = {}  # (base, conj_type) -> observed count
    for sent in train:
        for surface, pos, conj_type, _form, base in sent:
            if not _is_cjk_word(surface) or len(surface) > 8:
                # >8 chars is never a real ipadic word — it is the ipadic
                # unknown-word handler emitting a whole unanalyzable run
                # as one 名詞 (e.g. a 17-char hiragana fragment in
                # Botchan); shipping those as entries would also inflate
                # the Viterbi's max_word_len scan window
                continue
            freqs[surface] += 1
            if conj_type != "*" and base != "*" and _is_cjk_word(base):
                key = (base, conj_type)
                lexemes[key] = lexemes.get(key, 0) + 1

    # authored vocabulary (word freq [conj_type])
    n_auth = 0
    if os.path.exists(VOCAB):
        for line in open(VOCAB, encoding="utf-8"):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            w, f = parts[0], int(parts[1])
            if f <= 0 or not _is_cjk_word(w):
                continue
            freqs[w] = max(freqs[w], f)
            n_auth += 1
            if len(parts) > 2:
                lexemes[(w, parts[2])] = max(
                    lexemes.get((w, parts[2]), 0), f)

    # hand core from cjk_lexicon (floor frequency)
    from deeplearning4j_tpu.nlp.cjk_lexicon import JAPANESE_CORE
    for w in JAPANESE_CORE:
        if _is_cjk_word(w) and w not in freqs:
            freqs[w] = 20

    # conjugation expansion: every form of every seen lexeme, at a
    # discount of its lexeme count (never overriding observed counts)
    n_exp = 0
    for (base, conj_type), cnt in lexemes.items():
        for form in expand(base, conj_type):
            if not _is_cjk_word(form):
                continue
            disc = max(2, cnt // 3)
            if form not in freqs:
                n_exp += 1
            freqs[form] = max(freqs[form], disc)

    # mined Sino-Japanese compounds from jieba dict.txt. Identity
    # mappings come from DATA, not just the hand table: every kanji
    # observed in genuine Japanese text (the Botchan corpus + authored
    # vocabulary + hand core) is a valid Japanese character — a
    # simplified-only char (们/这/么) can never appear there, so any
    # unmapped char outside this set drops the word.
    ja_chars = set()
    for w in freqs:
        for c in w:
            if _is_han(c):
                ja_chars.add(c)
    for c, m in list(ZH2JA.items()):
        if c == m and c not in ja_chars:
            ja_chars.add(c)
    n_mined = 0
    try:
        import jieba
        dict_path = os.path.join(os.path.dirname(jieba.__file__), "dict.txt")
        for line in open(dict_path, encoding="utf-8"):
            parts = line.split()
            if len(parts) < 2 or not _is_han(parts[0]):
                continue
            w, f = parts[0], int(parts[1])
            if len(w) < 2 or len(w) > 5 or f < 18:
                continue
            mapped = []
            ok = True
            for c in w:
                if c in ZH2JA and ZH2JA[c] != c:
                    mapped.append(ZH2JA[c])
                elif c in ja_chars:
                    mapped.append(c)
                else:
                    # no confident mapping and never seen in Japanese
                    # text: drop the whole word
                    ok = False
                    break
            if not ok:
                continue
            ja = "".join(mapped)
            if ja not in freqs:
                n_mined += 1
                freqs[ja] = min(150, max(3, f // 200))
    except ImportError:
        pass

    # POS table for nlp/annotation.py's PosAnnotator: surface -> most
    # frequent ipadic top-level POS observed in the corpus
    if write:
        pos_counts = {}
        for sent in train:
            for surface, pos, *_ in sent:
                if _is_cjk_word(surface) and len(surface) <= 8:
                    pos_counts.setdefault(surface, Counter())[pos] += 1
        pos_out = os.path.join(os.path.dirname(OUT), "ja_pos.txt")
        with open(pos_out, "w", encoding="utf-8") as f:
            f.write("# surface -> most frequent ipadic top-level POS\n"
                    "# (from the convention-merged Botchan corpus; built\n"
                    "# by scripts/grow_ja_lexicon.py)\n")
            for w, c in sorted(pos_counts.items()):
                f.write(f"{w} {c.most_common(1)[0][0]}\n")
        print(f"wrote {len(pos_counts)} POS entries -> {pos_out}")

    if write:
        entries = sorted(freqs.items(), key=lambda kv: (-kv[1], kv[0]))
        with open(OUT, "w", encoding="utf-8") as f:
            f.write(
                "# Generated by scripts/grow_ja_lexicon.py. Sources:\n"
                "#  - ipadic-segmented Botchan + jawiki sentences (the\n"
                "#    reference's kuromoji test corpora; convention-merged\n"
                "#    frequencies, dev slice held out),\n"
                "#  - conjugation-paradigm expansion (ja_conjugation.py),\n"
                "#  - knowledge-authored ja_base_vocab.txt,\n"
                "#  - Sino-Japanese compounds mined from jieba dict.txt\n"
                "#    via simplified->shinjitai mapping (discounted).\n"
                "# Format: word<space>frequency per line.\n")
            f.write("\n".join(f"{w} {fr}" for w, fr in entries) + "\n")
        print(f"wrote {len(freqs)} entries -> {OUT}")
        print(f"  corpus surfaces: {sum(1 for s in train for _ in s)} tokens"
              f", authored: {n_auth}, expanded new: {n_exp}, "
              f"mined new: {n_mined}, dev sentences: {len(dev)}")
    return freqs, dev


def evaluate(dev, factory):
    from deeplearning4j_tpu.nlp.cjk import segmentation_scores
    gold = [[s for s, *_ in sent] for sent in dev]
    return segmentation_scores(factory, gold)


def tune():
    """Grid-search unknown penalties on the held-out Botchan dev slice."""
    import itertools

    from deeplearning4j_tpu.nlp import cjk

    _freqs, dev = build(write=True)
    best = None
    # grid centered on the shipped defaults (16/16/8/15) — the r5 search
    # ran coarse 6-15 first, then extended upward to the 0.855 plateau;
    # this grid reproduces that optimum region directly
    for kata, kanj1, kanjL, hira in itertools.product(
            (12.0, 16.0, 20.0), (13.0, 16.0, 20.0),
            (6.0, 8.0, 11.0), (12.0, 15.0, 18.0)):
        f = cjk.JapaneseUnigramTokenizerFactory(
            unk_katakana=kata, unk_kanji_first=kanj1,
            unk_kanji_char=kanjL, unk_hiragana=hira)
        sc = evaluate(dev, f)
        row = (sc["f1"], kata, kanj1, kanjL, hira)
        print(f"kata={kata} kanji1={kanj1} kanjiL={kanjL} hira={hira}"
              f" -> P {sc['precision']} R {sc['recall']} F1 {sc['f1']}")
        if best is None or row > best:
            best = row
    print(f"BEST: F1={best[0]} kata={best[1]} kanji1={best[2]} "
          f"kanjiL={best[3]} hira={best[4]}")


if __name__ == "__main__":
    if "--tune" in sys.argv:
        tune()
    else:
        build(write=True)
