"""Shared chip-script prologue: --smoke CPU pin or wedge-safe TPU probe.

Every on-chip experiment script starts the same way; the copies had
already drifted (one lost the compile-cache env var, exit styles
differed), so the prologue lives here once. Import and call BEFORE
importing jax anywhere else:

    from chiputil import smoke_or_probe
    SMOKE = smoke_or_probe()
"""

import os
import sys
import threading


def smoke_or_probe(timeout: float = 90.0) -> bool:
    """--smoke: pin jax to CPU, return True. Otherwise probe the chip via
    a daemon-thread watchdog (a wedged tunnel hangs jax.devices()
    machine-wide) and hard-exit 3 on WEDGED — ``os._exit``, because a
    plain SystemExit can hang joining PJRT threads (tpu_probe.py).

    Sets JAX_COMPILATION_CACHE_DIR before jax initializes either way, so
    chip runs keep the persistent compile cache."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/dl4j_tpu_jax_cache")
    if "--smoke" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    out = {}

    def probe():
        import jax

        out["d"] = jax.devices()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if "d" not in out:
        print("WEDGED", flush=True)
        os._exit(3)
    print("devices:", out["d"], flush=True)
    return False
