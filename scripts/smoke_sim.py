#!/usr/bin/env python
"""CI smoke sim: the ISSUE-11 acceptance surface for the trace-replay
simulator + serving-config autotuner, end to end on CPU.

1. **Replay determinism** — the seeded smoke workload expands to a
   byte-identical trace across two independent generations (and survives
   a save/load roundtrip), a different seed produces a different trace,
   and two fresh ``VirtualReplayer`` runs emit byte-identical reports.
2. **Tuning pressure** — on an 80 rps overload variant of the smoke
   workload the successive-halving tuner's winner must score >= the
   hand-picked default (it does so by construction: the default is
   candidate 0 and is never eliminated) and the winner must also hold up
   on the nominal-rate trace; a second search from the same seed must
   reproduce the same winner bit-for-bit. Every shed in the winner's
   report must carry a typed cause (typed-errors-only run).
3. **Tuned-config boot** — the winner persists into a fresh AOT store
   via ``record_winner`` and a cold ``FleetRegistry(tuned_for=...)``
   boot resolves it (``sim_tuned_config_hits_total`` == 1) and applies
   its engine/gen groups as per-model defaults.
4. **Open-loop live replay** — the booted 2-model fleet then serves the
   nominal trace at trace-scheduled wall times (never closed-loop);
   every fate must be a success or a *typed* shed, zero untyped errors.

Artifacts land in $CI_ARTIFACTS_DIR (default: ./ci-artifacts/):
smoke_sim_trace.txt (the replayed trace), smoke_sim_report.json (the
winner's deterministic virtual report), smoke_sim_live_report.json (the
live run's report), smoke_sim_metrics.prom (the fleet scrape, with the
tuned-config hit counter), all promcheck-validated.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _determinism(out_dir):
    """Same seed => byte-identical trace; different seed => different;
    save/load roundtrip is exact; virtual reports are byte-identical."""
    from deeplearning4j_tpu.sim import (VirtualReplayer, Trace,
                                        generate_trace, report_json,
                                        smoke_spec)

    spec = smoke_spec(seed=0, duration_s=30.0)
    t1, t2 = generate_trace(spec), generate_trace(spec)
    assert t1.to_bytes() == t2.to_bytes(), "same seed diverged"
    assert t1.content_hash() == t2.content_hash()
    t_other = generate_trace(smoke_spec(seed=1, duration_s=30.0))
    assert t_other.to_bytes() != t1.to_bytes(), "different seed identical"
    assert t_other.fingerprint() != t1.fingerprint()

    path = os.path.join(out_dir, "smoke_sim_trace.txt")
    t1.save(path)
    assert Trace.load(path).to_bytes() == t1.to_bytes(), "roundtrip drift"

    r1 = report_json(VirtualReplayer(t1).run())
    r2 = report_json(VirtualReplayer(t1).run())
    assert r1 == r2, "virtual replay report not byte-identical"
    return t1


def _tune(tune_trace, live_trace, out_dir):
    """Search the overload trace; the winner must beat (or tie) the
    default on BOTH traces, reproduce deterministically, and shed only
    typed causes."""
    from deeplearning4j_tpu.sim import (TYPED_CAUSES, Tuner,
                                        VirtualReplayer, report_json)

    tuner = Tuner(tune_trace, seed=0)
    res = tuner.search()
    assert res.winner_score >= res.default_score, \
        (res.winner_score, res.default_score)

    res2 = Tuner(tune_trace, seed=0).search()
    assert res2.winner == res.winner, "tuner search not deterministic"
    assert res2.winner_score == res.winner_score

    # the overload winner must not regress the nominal-rate workload
    light_w = VirtualReplayer(live_trace, knobs=res.winner).run()
    light_d = VirtualReplayer(live_trace).run()
    assert light_w["score"] >= light_d["score"], \
        (light_w["score"], light_d["score"])

    # typed-errors-only: every shed cause in the winner's full report is
    # a known typed cause, and nothing fell through to "internal"
    rep = res.winner_report
    assert rep["untyped_errors"] == 0, rep["untyped_errors"]
    bad = set(rep["shed"]) - set(TYPED_CAUSES)
    assert not bad, f"untyped shed causes: {bad}"

    with open(os.path.join(out_dir, "smoke_sim_report.json"), "w") as f:
        f.write(report_json(rep))
    return res


def _tuned_boot(store, tune_trace, res):
    """Cold FleetRegistry boot resolves the persisted winner from the AOT
    store and counts the hit."""
    from deeplearning4j_tpu.fleet import FleetRegistry
    from deeplearning4j_tpu.sim import record_winner

    key = record_winner(store, tune_trace, res)
    assert key, "record_winner failed to persist"

    fleet = FleetRegistry(aot_store=store, tuned_for=tune_trace.fingerprint())
    assert fleet.tuned_config == res.winner, "boot resolved a different config"
    series = fleet.metrics.snapshot().get(
        "sim_tuned_config_hits_total", {}).get("series", [])
    hits = sum(s["value"] for s in series)
    assert hits == 1, f"expected 1 tuned-config hit, saw {hits}"

    # a fingerprint nobody tuned must be a clean miss, not a crash
    other = FleetRegistry(aot_store=store, tuned_for="0" * 16)
    assert other.tuned_config is None
    misses = sum(s["value"] for s in other.metrics.snapshot().get(
        "sim_tuned_config_misses_total", {}).get("series", []))
    assert misses == 1, f"expected 1 tuned-config miss, saw {misses}"
    return fleet


def _live_replay(fleet, live_trace, out_dir):
    """Open-loop replay of the nominal trace against the tuned fleet."""
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.sim import (TYPED_CAUSES, FleetTarget,
                                        LiveReplayer)

    for name, seed in (("alpha", 0), ("beta", 1)):
        m = CausalLM(seed=seed, input_shape=(16,), num_layers=2, d_model=32,
                     num_heads=4, vocab=50).build()
        m.init()
        opts = {"gen_opts": {"capacity": 32}} if name == "beta" else {}
        fleet.add(name, m, input_dtype=np.int32,
                  engine_opts={"batch_buckets": (1, 2, 4, 8)}, **opts)

    # tuned gen knobs reached the per-model defaults; the explicit
    # capacity override above still wins
    beta_gen = fleet.get("beta").gen_opts
    assert beta_gen["slots"] == fleet.tuned_config["gen"]["slots"], beta_gen
    assert beta_gen["capacity"] == 32, beta_gen

    for tenant, slo, rate in (("acme", "gold", 500.0),
                              ("globex", "standard", 500.0),
                              ("free", "batch", 50.0)):
        fleet.tenants.register(tenant, rate_per_s=rate, slo=slo)

    try:
        # prewarm: page both models in and trace the generate path once so
        # first-token latencies measure serving, not XLA compiles
        fleet.ensure("alpha")
        fleet.ensure("beta")
        fleet.predict("alpha", np.zeros((1, 16), np.int64), tenant="acme")
        fleet.submit_generate("beta", np.array([1, 2, 3], np.int64), 4,
                              tenant="acme", temperature=0.0).wait()

        target = FleetTarget(fleet, input_len=16,
                             vocab=live_trace.spec.vocab)
        report = LiveReplayer(live_trace, target).run()

        assert report["requests"] == len(live_trace)
        assert report["untyped_errors"] == 0, \
            f"{report['untyped_errors']} untyped error(s): {report['shed']}"
        bad = set(report["shed"]) - set(TYPED_CAUSES)
        assert not bad, f"untyped live shed causes: {bad}"
        assert report["completed"] > 0

        with open(os.path.join(out_dir, "smoke_sim_live_report.json"),
                  "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
        scrape = fleet.metrics.to_prometheus()
        assert "sim_tuned_config_hits_total" in scrape
        with open(os.path.join(out_dir, "smoke_sim_metrics.prom"), "w") as f:
            f.write(scrape)
        return report
    finally:
        fleet.shutdown()


def main() -> int:
    out_dir = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(out_dir, exist_ok=True)

    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.sim import generate_trace, smoke_spec

    live_trace = _determinism(out_dir)
    print(f"smoke_sim: determinism OK — {len(live_trace)} events, "
          f"workload {live_trace.fingerprint()}, byte-identical trace "
          f"+ report across regenerations")

    tune_trace = generate_trace(smoke_spec(seed=0, base_rate_rps=80.0))
    res = _tune(tune_trace, live_trace, out_dir)
    print(f"smoke_sim: tuner OK — winner {res.winner_score:.6f} >= "
          f"default {res.default_score:.6f} on {len(tune_trace)} overload "
          f"events ({res.evaluated} evaluations), typed sheds only")

    store = AotStore(os.path.join(out_dir, "sim_aot_store"))
    fleet = _tuned_boot(store, tune_trace, res)
    print(f"smoke_sim: tuned boot OK — winner persisted for workload "
          f"{tune_trace.fingerprint()} and resolved on a cold boot "
          f"(1 hit, skewed fingerprint is a clean miss)")

    report = _live_replay(fleet, live_trace, out_dir)
    print(f"smoke_sim: live replay OK — {report['completed']}/"
          f"{report['requests']} completed open-loop in "
          f"{report['wall_s']}s wall, 0 untyped errors, "
          f"ttft_p50 {report['ttft_ms']['p50']}ms")

    import glob

    from deeplearning4j_tpu.obs.promcheck import check_file

    paths = sorted(glob.glob(os.path.join(out_dir, "smoke_sim*.prom")))
    assert paths, "no scrape artifacts written"
    bad = {p: check_file(p)[:3] for p in paths if check_file(p)}
    assert not bad, f"invalid scrape artifacts: {bad}"
    print(f"smoke_sim: promcheck OK over {len(paths)} scrape artifact(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
