#!/usr/bin/env python
"""encoded_gradients wire/step-time microbench (r3 VERDICT #6).

The reference's codec existed because it was MEASURED to pay off on its
transport (EncodingHandler.java:139 over Aeron UDP). This script produces the
equivalent evidence for the TPU-native port:

1. **Wire model (exact, per step per worker)** — dense ring all-reduce vs
   compressed all-gather:
   - dense fp32:       2 * (n-1)/n * size * 4 bytes  (~8*size for large n)
   - quantized:        n * capacity * (4 + 1) bytes  (int32 index + int8 sign)
   - exact top-k:      n * capacity * (4 + 4) bytes  (int32 index + f32 value)
   Break-even capacity_frac (quantized) = 8 / (5 * n).

2. **Measured step time** on the virtual CPU mesh — dense `shared_gradients`
   vs `encoded_gradients` at several capacity_frac values, on an MLP sized
   by --params. The CPU mesh's "wire" is shared memory, so this measures the
   COMPUTE overhead of encode/decode (top_k + scatter) — the floor any
   transport pays; it cannot show DCN bandwidth wins (run on a multi-slice
   pod for that).

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/bench_encoded.py [--params 1000000] [--steps 10]
"""

import argparse
import json
import time

import numpy as np


def wire_model(size: int, n: int, capacity_frac: float) -> dict:
    cap = max(1, int(size * capacity_frac))
    dense = 2 * (n - 1) / n * size * 4
    quant = n * cap * 5
    topk = n * cap * 8
    return {
        "size": size, "n_workers": n, "capacity_frac": capacity_frac,
        "dense_bytes_per_worker": int(dense),
        "quantized_bytes_per_worker": int(quant),
        "topk_bytes_per_worker": int(topk),
        "quantized_vs_dense": round(quant / dense, 4),
        "breakeven_capacity_frac_quantized": round(8 / (5 * n), 4),
    }


def measure(params_target: int, steps: int, n: int) -> list:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.data.iterators import DataSet
    from deeplearning4j_tpu.nn import NetConfig, SequentialBuilder
    from deeplearning4j_tpu.nn import layers as L
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

    # square-ish MLP hitting ~params_target parameters
    h = int(np.sqrt(params_target / 2))
    d_in, d_out = h, 10

    def build():
        return (SequentialBuilder(NetConfig(seed=0, updater={"type": "adam",
                                                             "learning_rate": 1e-3}))
                .input_shape(d_in)
                .layer(L.Dense(n_out=h, activation="relu"))
                .layer(L.Dense(n_out=h, activation="relu"))
                .layer(L.Output(n_out=d_out, activation="softmax", loss="mcxent"))
                .build())

    rng = np.random.RandomState(0)
    B = 8 * n
    x = rng.randn(B, d_in).astype(np.float32)
    y = np.eye(d_out, dtype=np.float32)[rng.randint(0, d_out, B)]
    mesh = make_mesh({"data": n}, jax.devices()[:n])

    def time_mode(**kw):
        pw = ParallelWrapper(build(), mesh=mesh, seed=0, **kw)
        size = sum(int(v.size) for v in jax.tree_util.tree_leaves(pw.model.params))

        def one_step():
            return pw._fit_batch(x, y)

        loss = one_step()  # compile
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = one_step()
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / steps, size

    out = []
    t_dense, size = time_mode(mode="shared_gradients")
    out.append({"mode": "shared_gradients", "params": size,
                "step_ms": round(t_dense * 1e3, 2)})
    for frac in (0.01, 0.05, 0.25):
        t_enc, _ = time_mode(mode="encoded_gradients", threshold=1e-5,
                             capacity_frac=frac, quantize=True)
        out.append({"mode": "encoded_gradients", "capacity_frac": frac,
                    "params": size, "step_ms": round(t_enc * 1e3, 2),
                    "vs_dense": round(t_enc / t_dense, 3),
                    **{k: v for k, v in wire_model(size, n, frac).items()
                       if "bytes" in k or "vs" in k}})
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--params", type=int, default=1_000_000)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--wire-only", action="store_true")
    args = ap.parse_args()

    # the wire table the PERF.md guidance is derived from: ResNet-50 scale
    for n in (8, 32, 256):
        for frac in (0.01, 0.05):
            print(json.dumps({"wire_model": wire_model(25_600_000, n, frac)}))
    if not args.wire_only:
        for row in measure(args.params, args.steps, args.workers):
            print(json.dumps(row))


if __name__ == "__main__":
    main()
