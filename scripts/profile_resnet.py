#!/usr/bin/env python
"""Ablation profiler for the ResNet-50 bench: where does the step time go?

Times variants of the ResNet-50 train step on the real chip with the same
two-point measurement bench.py uses (slope cancels fixed tunnel RTT):
  full      : the exact bench train step
  fwd_loss  : forward + loss, no backward, no optimizer
  fwd_infer : inference forward (training=False, running stats)
  sgd       : train step with plain SGD (isolates adam cost)
  nobn      : train step on a BN-free ResNet-50 (BN folded away)
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.models.cnn import _net_config
from deeplearning4j_tpu.nn.model import GraphBuilder
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import vertices as V
from deeplearning4j_tpu.train import Trainer

BATCH = 128
IMG = 224


def resnet50_nobn(seed=0):
    g = GraphBuilder(_net_config(seed)).add_input("in", (IMG, IMG, 3))

    def conv(name, inp, n_out, k, stride=1, act="relu"):
        g.add_layer(name, L.Conv2D(n_out=n_out, kernel=(k, k), stride=(stride, stride),
                                   padding="same", use_bias=True, activation=act), inp)
        return name

    def bottleneck(name, inp, mid, out, stride=1, project=False):
        a = conv(f"{name}_a", inp, mid, 1, stride)
        b = conv(f"{name}_b", a, mid, 3)
        c = conv(f"{name}_cc", inp=b, n_out=out, k=1, act="identity")
        sc = conv(f"{name}_proj", inp, out, 1, stride, act="identity") if project else inp
        g.add_vertex(f"{name}_add", V.ElementWise(op="add"), c, sc)
        g.add_layer(name, L.ActivationLayer(activation="relu"), f"{name}_add")
        return name

    x = conv("stem", "in", 64, 7, stride=2)
    g.add_layer("pool1", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
    x = "pool1"
    for si, (blocks, mid, out, stride) in enumerate(
            [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]):
        for bi in range(blocks):
            x = bottleneck(f"s{si}b{bi}", x, mid, out,
                           stride=stride if bi == 0 else 1, project=bi == 0)
    g.add_layer("gap", L.GlobalPooling(mode="avg"), x)
    g.add_layer("out", L.Output(n_out=1000, activation="softmax", loss="mcxent"), "gap")
    return g.set_outputs("out").build()


def timeit(fn, *args, steps=16):
    """Two-point slope timing; fn must return device values; we chain by
    re-feeding nothing (args fixed) and syncing via one readback at the end."""
    outs = fn(*args)
    jax.block_until_ready(outs)

    def run(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = fn(*args)
        jax.block_until_ready(o)
        return time.perf_counter() - t0

    t1 = run(max(steps // 4, 1))
    t2 = run(steps)
    return (t2 - t1) / (steps - max(steps // 4, 1))


def timeit_step(step, params, opt_state, state, x, y, rng, steps=16):
    p, o, s, loss = step(params, opt_state, state, x, y, rng)
    float(loss)

    def run(k, p, o, s):
        t0 = time.perf_counter()
        for _ in range(k):
            p, o, s, loss = step(p, o, s, x, y, rng)
        float(loss)
        return time.perf_counter() - t0, p, o, s

    k1, k2 = max(steps // 4, 1), steps
    t1, p, o, s = run(k1, p, o, s)
    t2, p, o, s = run(k2, p, o, s)
    return (t2 - t1) / (k2 - k1)


def build(model_ctor, updater=None):
    zm = model_ctor(num_classes=1000, seed=0, input_shape=(IMG, IMG, 3))
    model = zm.build()
    model.config.compute_dtype = "bfloat16"
    if updater:
        model.config.updater = updater
    model.init()
    tr = Trainer(model)
    return model, tr


def main():
    x = np.random.RandomState(0).rand(BATCH, IMG, IMG, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[np.random.RandomState(1).randint(0, 1000, BATCH)]
    x, y = jax.device_put(x), jax.device_put(y)
    rng = jax.random.PRNGKey(0)
    results = {}

    model, tr = build(ResNet50)

    @jax.jit
    def fwd_loss(params, state, x, y, rng):
        loss, _ = model.score(params, state, x, y, training=True, rng=rng)
        return loss

    results["fwd_loss"] = timeit(fwd_loss, tr.params, tr.state, x, y, rng)

    @jax.jit
    def fwd_infer(params, state, x):
        ys, _ = model.forward(params, state, x, training=False)
        return ys[0]

    results["fwd_infer"] = timeit(fwd_infer, tr.params, tr.state, x)

    # the donating step goes LAST for this trainer: it deletes tr.params
    step = tr._make_step()
    results["full"] = timeit_step(step, tr.params, tr.opt_state, tr.state, x, y, rng)

    model_sgd, tr_sgd = build(ResNet50, updater={"type": "sgd", "learning_rate": 1e-2})
    step_sgd = tr_sgd._make_step()
    results["sgd"] = timeit_step(step_sgd, tr_sgd.params, tr_sgd.opt_state, tr_sgd.state, x, y, rng)

    nob = resnet50_nobn()
    nob.config.compute_dtype = "bfloat16"
    nob.init()
    tr_nob = Trainer(nob)
    step_nob = tr_nob._make_step()
    results["nobn"] = timeit_step(step_nob, tr_nob.params, tr_nob.opt_state, tr_nob.state, x, y, rng)

    for k, v in results.items():
        print(f"{k:10s} {v * 1e3:8.2f} ms/step   {BATCH / v:9.1f} img/s")


if __name__ == "__main__":
    main()
