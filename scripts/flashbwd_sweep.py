#!/usr/bin/env python
"""Sweep the Mosaic flash-backward block cap vs the XLA scan backward on
chip, in the regimes that matter: BERT fine-tune (T=512) and long-context
(T=2048..8192). Decides the BACKWARD default.

Timing discipline: `jax.block_until_ready` proved unreliable through the
axon tunnel (flat 0.04ms for workloads that differ 100x in FLOPs), so every
measurement forces a scalar device->host readback that depends on all three
gradients — that fetch cannot complete before the computation has."""
import sys
import time

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

from chiputil import smoke_or_probe

SMOKE = smoke_or_probe()  # CPU shape/signature shakeout: tiny sizes,
#                           no probe, xla backward only (the Mosaic
#                           kernel is TPU-only) — run before a chip
#                           window so the real sweep can't die on a
#                           Python error

import jax
import jax.numpy as jnp
import numpy as np

import deeplearning4j_tpu.ops.flash_attention as fa


def timed(backend, B, T, H, D, iters=10, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), dtype) for _ in range(3))

    @jax.jit
    def g(q, k, v, carry):
        # carry chains iteration i to i-1 (value-neutral: *0), so the ONE
        # host fetch after the loop transitively waits for every
        # iteration — no per-iteration RTT stall, and no reliance on
        # block_until_ready (unreliable through the tunnel) or on
        # enqueue-order guarantees.
        def loss(q, k, v):
            return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                              backward=backend) ** 2)
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(
            q + (carry * 0).astype(q.dtype), k, v)
        return (jnp.sum(dq.astype(jnp.float32)) + jnp.sum(dk.astype(jnp.float32))
                + jnp.sum(dv.astype(jnp.float32)))

    carry = jnp.float32(0)
    carry = g(q, k, v, carry)  # compile + warm
    float(carry)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = g(q, k, v, carry)
    float(carry)  # the single sync point for the whole chain
    dt = (time.perf_counter() - t0) / iters
    return dt * 1e3


CONFIGS = ([(1, 256, 2, 32)] if SMOKE
           else [(32, 512, 12, 64), (2, 2048, 8, 64), (2, 4096, 8, 64),
                 (1, 8192, 8, 64)])
for B, T, H, D in CONFIGS:
    kw = {"iters": 2, "dtype": jnp.float32} if SMOKE else {}
    tx = timed("xla", B, T, H, D, **kw)
    print(f"B{B} T{T}: xla {tx:.2f}ms", flush=True)
    for cap in () if SMOKE else (256, 512, 1024):
        fa.BWD_BLOCK_CAP = cap
        jax.clear_caches()  # cap is a trace-time constant; force retrace
        tp = timed("pallas", B, T, H, D, **kw)
        print(f"  pallas@{cap} {tp:.2f}ms ({tx/tp:.2f}x)", flush=True)
print("DONE")
