#!/usr/bin/env python
"""ParallelWrapper allreduce-bandwidth driver metric (BASELINE.md row 4,
ref ParallelWrapper.java:467 — the NCCL allreduce the reference times).

Multi-chip ICI is not reachable from this host (one tunneled v5e chip), so
the metric decomposes into the two measurable parts:

1. REAL CHIP — the GSPMD-fused cost on the compute side: step-time delta
   between a plain ResNet-50 train step and the identical step wrapped in
   the ParallelWrapper shared_gradients program on a 1-device mesh. On one
   device XLA elides the all-reduce, so the delta is the wrapper's whole
   residual overhead (sharding constraints, program structure) — the
   correct single-chip number, and it should be ~0.

2. VIRTUAL 8-DEVICE MESH (CPU) — the collective is real (ring all-reduce
   over shared memory): time psum of a ResNet-50-sized gradient pytree
   (25.6M f32) alone, giving the per-step collective cost floor the
   wrapper adds when the wire is infinitely fast, plus the wire model:
   ring all-reduce moves 2(n-1)/n * 4B/param; at v5e ICI 1.6 Tbps/link
   (2 links/axis duplex) the 25.6M-param reduce is sub-millisecond —
   overlap with the 15.9ms backward makes it free in steady state.
"""
import json
import os
import subprocess
import sys
import threading

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/scripts")

PARAMS_RESNET50 = 25_557_032  # our ResNet50 param count (matches ref zoo)


def wire_model(n, params=PARAMS_RESNET50, bytes_per=4,
               ici_GBps=200.0):
    """Ring all-reduce wire math at v5e ICI (1.6 Tbps/link duplex)."""
    mb = 2 * (n - 1) / n * params * bytes_per / 1e6
    return {"n": n, "MB_per_worker": round(mb, 1),
            "t_ms_at_ici": round(mb / 1e3 / ici_GBps * 1e3, 3)}


def real_chip():
    import time

    import jax
    import numpy as np

    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.train import Trainer

    rng = np.random.RandomState(0)
    x = rng.randn(128, 224, 224, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, 128)]

    def timed(fit_one, iters=10):
        # steps chain through trainer/wrapper state, so one final D2H
        # readback syncs the whole loop (block_until_ready lies through
        # the tunnel; per-iteration float() would add RTT per step)
        float(fit_one())  # compile + warm
        t0 = time.perf_counter()
        loss = None
        for _ in range(iters):
            loss = fit_one()
        float(loss)
        return (time.perf_counter() - t0) / iters * 1e3

    m = ResNet50(num_classes=1000, seed=0).build()
    m.config.compute_dtype = "bfloat16"
    m.init()
    tr = Trainer(m)
    step = tr._make_step()
    key = jax.random.PRNGKey(0)

    def plain_one():
        nonlocal_state["p"], nonlocal_state["o"], nonlocal_state["s"], loss = \
            step(nonlocal_state["p"], nonlocal_state["o"],
                 nonlocal_state["s"], x, y, key, None, None)
        return loss

    nonlocal_state = {"p": tr.params, "o": tr.opt_state, "s": tr.state}
    t_plain = timed(plain_one)

    m2 = ResNet50(num_classes=1000, seed=0).build()
    m2.config.compute_dtype = "bfloat16"
    m2.init()
    pw = ParallelWrapper(m2, mode="shared_gradients")
    t_pw = timed(lambda: pw._fit_batch(x, y))
    return {"plain_step_ms": round(t_plain, 2),
            "pw_shared_gradients_step_ms": round(t_pw, 2),
            "wrapper_overhead_ms": round(t_pw - t_plain, 2)}


def virtual_mesh():
    """Run in a subprocess with an 8-device CPU mesh; time bare psum of a
    ResNet-50-sized gradient tree."""
    code = r"""
import time
import jax, jax.numpy as jnp
# the env var alone is NOT enough here: the hosting image's sitecustomize
# registers the axon PJRT plugin and overrides jax_platforms, so devices()
# would dial the (possibly wedged) tunnel — the explicit config.update is
# what actually pins CPU (same as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map
devs = np.array(jax.devices()[:8])
mesh = Mesh(devs, ("dp",))
N = 25_557_032
# the FULL gradient buffer replicated on every worker (in_specs P(None)):
# each device contributes all 25.6M f32 values, exactly the
# ParallelWrapper shared_gradients wire pattern
g = jnp.ones((N,), jnp.float32)

@jax.jit
def reduce_only(g):
    def f(g):
        return jax.lax.psum(g, "dp")
    r = shard_map(f, mesh=mesh, in_specs=P(None), out_specs=P(None))(g)
    # scalar readback below is the sync point (block_until_ready measured
    # unreliable for timing; see flashbwd_sweep.py)
    return r, jnp.sum(r[::4097])

r, s = reduce_only(g); float(s)
t0 = time.perf_counter()
for _ in range(5):
    r, s = reduce_only(g)
    float(s)
dt = (time.perf_counter() - t0) / 5
mb = 2 * 7 / 8 * N * 4 / 1e6  # ring all-reduce: 2(n-1)/n of the buffer
print(f"RESULT {dt*1e3:.2f} {mb:.0f}")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            ms, mb = line.split()[1:]
            return {"psum_ms_8dev_cpu": float(ms),
                    "ring_MB_per_worker": float(mb),
                    "note": "full 25.6M-param buffer replicated per worker; "
                            "CPU shared-memory ring; collective overhead "
                            "floor, not ICI wire"}
    return {"error": out.stderr[-300:]}


if __name__ == "__main__":
    res = {"wire_model": [wire_model(n) for n in (4, 8, 32)],
           "virtual_mesh": virtual_mesh()}
    on_tpu = "--cpu-only" not in sys.argv
    if on_tpu:
        out = {}
        def probe():
            import jax
            out["d"] = jax.devices()
        t = threading.Thread(target=probe, daemon=True)
        t.start(); t.join(90)
        if "d" not in out:
            print("WEDGED (skipping real-chip part)")
        else:
            res["real_chip"] = real_chip()
    print(json.dumps(res, indent=1))
    with open("/tmp/allreduce_bench.json", "w") as f:
        json.dump(res, f, indent=1)
