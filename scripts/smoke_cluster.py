#!/usr/bin/env python
"""CI smoke cluster: boot a 2-replica fleet behind the cluster router,
kill a replica mid-traffic, and assert the cluster SURVIVES — the
ISSUE-10 acceptance surface.

The drill (deterministic, seeded, CPU-only; membership and SLO burn run
on an injectable skewable clock so death detection and burn-window aging
never wait on wall time):

- **A. reference pass** — gold + standard tenants predict and generate
  through the router; the fault-free answers become the ground truth every
  later phase is compared against (zero wrong-params tolerance).
- **B. hedge drill** — a scoped chaos delay makes the predict primary
  slow; the gold request hedges to the other replica after ``hedge_ms``,
  the hedge wins, and the Perfetto export shows BOTH attempts stitched
  into the one request track (same trace id, ``hedge`` False and True).
- **C. kill a replica mid-traffic** — the generate primary is crash-killed
  (no drain) under mixed gold/standard load: every response is either
  bit-correct or a typed error (no raw 500s ever), membership marks the
  victim dead, placement re-plans onto the survivor, and the dead
  replica's model serves again from its new home.
- **D. partition the survivor** — a scoped connection fault makes the last
  replica unreachable: requests shed with a typed 503
  (``upstream_unreachable``) and the gold burn rate spikes above 1.0.
  The telemetry plane watches the same outage: federated scrapes mark
  the partitioned survivor ``error``/stale (never a scrape failure), the
  ``gold_burn_high`` alert goes pending, holds through its 20 s sustain
  window (NOT firing at +10 s — sustain semantics), then fires; the
  firing is visible on ``GET /v1/alerts``, the burn history on
  ``GET /v1/tsdb``, and the transition in the flight dump. Healing the
  partition and aging the window brings
  ``fleet_slo_burn_rate{slo_class="gold",window="1m"}`` back below 1.0
  and the alert RESOLVES — because the condition cleared, not because
  the window slid.
- **E. global tenant bucket** — a tenant capped at the router is refused
  with a typed 429 + Retry-After no matter which replica would serve it.

Artifacts: $CI_ARTIFACTS_DIR/smoke_cluster_metrics.prom (+ _om.prom, both
validated by obs.promcheck — now carrying the tsdb_*/alert_* families),
smoke_cluster_tsdb.json (a /v1/tsdb range query of the burn spike),
smoke_cluster_trace.json (Perfetto), and a flight_NN.json dump of the
drill's last requests.
"""

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

HEARTBEAT_S = 0.25
SUSPECT_AFTER_S = 2.0
DEAD_AFTER_S = 6.0
HEDGE_MS = 150.0
X = [[0.1, -0.2, 0.3, -0.4]]
PROMPT = [3, 1, 4, 1, 5]
GEN_BODY = {"prompt": PROMPT, "max_new_tokens": 6, "temperature": 0.0,
            "stream": False}

# membership + SLO burn share this skewable clock: bumping the skew ages
# heartbeat leases (instant, deterministic death detection) and slides the
# burn-rate window (bad events age out without waiting 60 real seconds)
CLOCK_SKEW = [0.0]


def _clock():
    return time.monotonic() + CLOCK_SKEW[0]


def _post(port, path, body, tenant=None):
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Tenant"] = tenant
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers=headers)
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read()), dict(r.headers)


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.status, r.read()


def _wait_ready(port, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            status, _ = _get(port, "/ready")
            if status == 200:
                return
        except (urllib.error.HTTPError, OSError):
            pass
        time.sleep(0.1)
    raise AssertionError(f"router not ready within {timeout_s}s")


def _metric(scrape: str, name: str, **labels) -> float:
    total = 0.0
    found = False
    for line in scrape.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in "{ ":
            continue  # a longer metric name sharing this prefix
        if not all(f'{k}="{v}"' in rest for k, v in labels.items()):
            continue
        total += float(line.rsplit(" ", 1)[1])
        found = True
    assert found, f"metric {name}{labels or ''} missing from scrape"
    return total


def _build_replica(rid, store_dir):
    """One replica: its own fleet registry holding the dense model and the
    LM, mounting the SHARED AOT store directory (each replica gets its own
    handle over one directory, exactly as separate processes would). Seeds
    are shared across replicas so every replica computes the same answers —
    the smoke's wrong-params oracle."""
    from deeplearning4j_tpu.aot import AotStore
    from deeplearning4j_tpu.cluster import spawn_replica
    from deeplearning4j_tpu.fleet import FleetRegistry
    from deeplearning4j_tpu.models import CausalLM
    from deeplearning4j_tpu.nn.layers import Dense, Output
    from deeplearning4j_tpu.nn.model import NetConfig, Sequential

    dense = Sequential(NetConfig(seed=0),
                       [Dense(n_out=6, activation="tanh"),
                        Output(n_out=3, loss="mcxent", activation="softmax")],
                       (4,))
    dense.init()
    lm = CausalLM(seed=0, input_shape=(16,), num_layers=2, d_model=32,
                  num_heads=4, vocab=50).build()
    lm.init()
    fleet = FleetRegistry(aot_store=AotStore(store_dir))
    fleet.add("d", dense)
    fleet.add("g", lm, input_dtype=np.int32,
              gen_opts={"slots": 2, "capacity": 24, "seed": 0})
    return spawn_replica(rid, fleet)


def _typed_error(port, path, body, tenant=None):
    """POST expecting a typed error; returns (code, cause, headers)."""
    try:
        _post(port, path, body, tenant=tenant)
    except urllib.error.HTTPError as e:
        payload = json.loads(e.read())
        assert "cause" in payload, f"untyped {e.code} from {path}: {payload}"
        return e.code, payload["cause"], e.headers
    raise AssertionError(f"{path} unexpectedly succeeded")


def main():
    artifacts = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(artifacts, exist_ok=True)

    from deeplearning4j_tpu.chaos import FaultPlane, install, uninstall
    from deeplearning4j_tpu.cluster import ClusterRouter
    from deeplearning4j_tpu.obs import (AlertEngine, FederatedScraper,
                                        TimeSeriesStore)
    from deeplearning4j_tpu.obs import flight as flight_mod
    from deeplearning4j_tpu.obs import reqtrace as reqtrace_mod
    from deeplearning4j_tpu.obs.flight import FlightRecorder
    from deeplearning4j_tpu.obs.promcheck import check_text
    from deeplearning4j_tpu.obs.reqtrace import (RequestTracer,
                                                 parse_traceparent)
    from deeplearning4j_tpu.obs.trace import Tracer

    # full observability: every routed request is traced end to end and the
    # flight recorder keeps the last-N records for the post-mortem bundle
    tracer = Tracer()
    recorder = flight_mod.install(FlightRecorder(out_dir=artifacts))
    reqtrace_mod.install(RequestTracer(tracer=tracer, flight=recorder))

    store_dir = tempfile.mkdtemp(prefix="smoke_cluster_aot_")
    replicas = {rid: _build_replica(rid, store_dir)
                for rid in ("r1", "r2")}
    router = ClusterRouter(port=0, heartbeat_s=HEARTBEAT_S,
                           suspect_after_s=SUSPECT_AFTER_S,
                           dead_after_s=DEAD_AFTER_S, hedge_ms=HEDGE_MS,
                           clock=_clock)
    for rid, h in replicas.items():
        router.add_replica(rid, h.base_url)
    # router-side GLOBAL buckets: gold + standard tenants with headroom,
    # plus one tenant capped tightly enough to refuse inside the drill
    router.tenants.register("vip", rate_per_s=100.0, slo="gold")
    router.tenants.register("std", rate_per_s=100.0, slo="standard")
    router.tenants.register("capped", rate_per_s=0.5, burst=2.0)
    router.start()
    port = router.port
    # telemetry plane on the same skewable clock: federated scrape of the
    # router + every replica into the in-process TSDB, with the default
    # alert ruleset evaluated after each pass (driven manually here — the
    # drill owns time, so no background scrape thread)
    tsdb = TimeSeriesStore(clock=_clock, metrics=router.metrics)

    # notifier fan-out under test: a capture channel records every
    # delivered notification so phase D can assert the dedup contract —
    # exactly ONE notification per distinct firing, however many
    # evaluation passes happen while the rule stays firing
    notifications = []

    class _CaptureNotifier:
        channel = "capture"

        def notify(self, event):
            notifications.append(event)

    engine = AlertEngine(tsdb, metrics=router.metrics, clock=_clock,
                         notifiers=(_CaptureNotifier(),), renotify_s=3600.0)
    scraper = FederatedScraper(router, tsdb, alerts=engine, clock=_clock)
    try:
        _wait_ready(port)
        router.poll_once()  # first beat round: collect payloads, build plan
        status, body = _get(port, "/v1/cluster")
        assert status == 200
        plan = json.loads(body)["placement"]
        assert set(plan) == {"d", "g"} and all(len(c) == 2
                                               for c in plan.values()), plan
        # healthy-cluster baseline scrape: every source answers, nothing
        # is stale, and no alert in the default ruleset has cause to fire
        outcomes = scraper.scrape_once()
        assert outcomes == {"router": "ok", "r1": "ok", "r2": "ok"}, outcomes
        assert not engine.active(), engine.active()

        # ---- A: fault-free reference pass (both tenants, both verbs)
        print("=== phase A: reference pass ===", flush=True)
        ref_pred, _ = _post(port, "/v1/models/d/predict", {"ndarray": X},
                            tenant="vip")
        ref_toks = _post(port, "/v1/models/g/generate?stream=false",
                         GEN_BODY, tenant="std")[0]["tokens"]
        assert ref_toks, "reference generation returned no tokens"

        # ---- B: slow primary -> gold hedge wins, one stitched trace
        print("=== phase B: gold hedge beats a slow primary ===", flush=True)
        d_primary, d_backup = plan["d"][0], plan["d"][1]
        fp = install(FaultPlane(seed=0, metrics=router.metrics))
        fp.inject_spec(
            f"cluster.transport:delay:delay_s=0.6,scope={d_primary},times=-1")
        t0 = time.monotonic()
        out, hdrs = _post(port, "/v1/models/d/predict", {"ndarray": X},
                          tenant="vip")
        hedge_elapsed = time.monotonic() - t0
        uninstall()
        assert np.allclose(out["output"], ref_pred["output"]), \
            "hedged predict changed the answer"
        assert hedge_elapsed < 0.55, \
            f"hedge did not beat the delayed primary ({hedge_elapsed:.2f}s)"
        parsed = parse_traceparent(hdrs.get("traceparent"))
        assert parsed is not None, "hedged response carried no traceparent"
        hedge_trace = parsed[0]
        time.sleep(0.8)  # let the cancelled loser finish its attempt stage
        atts = [e for e in tracer.events
                if e.get("id") == hedge_trace and e.get("name") == "attempt"
                and e.get("ph") == "b"]
        assert len(atts) >= 2, \
            f"hedged trace holds {len(atts)} attempt stage(s), wanted 2"
        assert {a["args"]["hedge"] for a in atts} == {False, True}
        assert {a["args"]["replica"] for a in atts} == {d_primary, d_backup}

        # ---- C: crash-kill the generate primary under mixed load
        print("=== phase C: kill a replica mid-traffic ===", flush=True)
        victim = plan["g"][0]
        survivor = plan["g"][1]
        # park the background detector: from here the drill drives
        # membership itself (poll_once), so the FIRST request after the
        # kill deterministically meets a dead socket and must fail over
        # rather than racing a heartbeat that already benched the victim
        router.heartbeat_s = 3600.0
        time.sleep(2 * HEARTBEAT_S)  # let any in-flight tick finish
        errors = []
        for i in range(24):
            if i == 6:
                replicas[victim].kill()
            for path, body, tenant, check in (
                    ("/v1/models/d/predict", {"ndarray": X}, "vip",
                     lambda o: np.allclose(o["output"],
                                           ref_pred["output"])),
                    ("/v1/models/g/generate?stream=false", GEN_BODY, "std",
                     lambda o: o["tokens"] == ref_toks)):
                try:
                    out, _ = _post(port, path, body, tenant=tenant)
                except urllib.error.HTTPError as e:
                    payload = json.loads(e.read())
                    assert e.code != 500 and "cause" in payload, \
                        f"raw/untyped error {e.code} from {path}: {payload}"
                    errors.append((e.code, payload["cause"]))
                else:
                    assert check(out), \
                        f"WRONG-PARAMS answer from {path} at iteration {i}"
        print(f"typed refusals during the kill window: {errors or 'none'}",
              flush=True)

        # deterministic death: age the victim's lease past dead_after_s and
        # run one poll round — the survivor's beat renews, the victim's
        # cannot, placement re-plans onto the survivor alone
        CLOCK_SKEW[0] += DEAD_AFTER_S + 1.0
        states = router.poll_once()
        assert states[victim] == "dead" and states[survivor] == "alive", \
            states
        status, body = _get(port, "/v1/cluster")
        view = json.loads(body)
        assert view["placement"]["g"] == [survivor], view["placement"]
        assert view["membership"][victim]["state"] == "dead"
        # ...and the dead replica's model is genuinely serving again
        toks = _post(port, "/v1/models/g/generate?stream=false", GEN_BODY,
                     tenant="std")[0]["tokens"]
        assert toks == ref_toks, "re-placed model diverged from reference"

        # ---- D: partition the survivor -> typed outage, burn spike, heal
        print("=== phase D: partition, burn spike, recovery ===", flush=True)
        # renew the survivor's lease so the scrape below meets an ALIVE
        # member behind a dead wire (the soft-stale "error" path), not a
        # member already benched as suspect by its aged lease
        router.poll_once()
        fp = install(FaultPlane(seed=0, metrics=router.metrics))
        fp.inject_spec(
            f"cluster.transport:error:type=connection,scope={survivor},"
            f"times=-1")
        # first scrape meets an ALIVE member behind a dead wire: the pull
        # soft-stales it and reports "error" — never a scrape crash. The
        # dead victim reports "stale" straight from membership state.
        outcomes = scraper.scrape_once()
        assert outcomes[survivor] == "error", outcomes
        assert outcomes[victim] == "stale", outcomes
        assert outcomes["router"] == "ok", outcomes
        assert "replica_dead" in engine.active(), engine.active()
        for _ in range(2):
            code, cause, hdrs = _typed_error(
                port, "/v1/models/d/predict", {"ndarray": X}, tenant="vip")
            assert code == 503 and cause == "upstream_unreachable", \
                (code, cause)
            assert hdrs.get("Retry-After") is not None

        # the two shed gold requests refreshed the burn gauge above 1.0,
        # so this scrape pass (which also evaluates the alert ruleset)
        # sends gold_burn_high to PENDING — not firing: its 20s sustain
        # has not elapsed.
        scraper.scrape_once()
        assert "gold_burn_high" not in engine.active(), \
            "gold_burn_high fired instantly, ignoring its for_s sustain"
        CLOCK_SKEW[0] += 10.0
        scraper.scrape_once()  # +10s: still inside the sustain window
        assert "gold_burn_high" not in engine.active(), \
            "gold_burn_high fired at +10s, before its 20s sustain elapsed"
        CLOCK_SKEW[0] += 11.0
        scraper.scrape_once()  # +21s: sustained past for_s -> FIRING
        assert "gold_burn_high" in engine.active(), \
            engine.snapshot()["rules"]["gold_burn_high"]
        status, body = _get(port, "/v1/alerts")
        assert status == 200
        alerts_view = json.loads(body)
        assert alerts_view["rules"]["gold_burn_high"]["state"] == "firing", \
            alerts_view["rules"]["gold_burn_high"]
        # the firing paged the capture channel exactly once; a further
        # evaluation pass while still firing is deduplicated (same
        # dedup key, renotify_s not yet elapsed)
        engine.evaluate()
        gb_fired = [n for n in notifications
                    if n["rule"] == "gold_burn_high"
                    and n["state"] == "firing"]
        assert len(gb_fired) == 1, gb_fired
        assert gb_fired[0]["dedup_key"].startswith("gold_burn_high@"), \
            gb_fired
        # ...and the burn history that drove the page is queryable over HTTP
        status, body = _get(
            port, "/v1/tsdb?name=fleet_slo_burn_rate"
                  "&label.slo_class=gold&label.window=1m")
        assert status == 200
        tsdb_view = json.loads(body)
        assert tsdb_view["series"] and all(
            s["points"] for s in tsdb_view["series"]), tsdb_view

        uninstall()
        scrape = _get(port, "/metrics")[1].decode()
        burn = _metric(scrape, "fleet_slo_burn_rate", model="d",
                       slo_class="gold", window="1m")
        assert burn > 1.0, f"gold burn did not spike: {burn}"
        # heal: age the bad events out of the 1m window, renew leases, and
        # serve gold traffic again — the refreshed gauge must drop below 1
        CLOCK_SKEW[0] += 61.0
        router.poll_once()
        for _ in range(5):
            out, _ = _post(port, "/v1/models/d/predict", {"ndarray": X},
                           tenant="vip")
            assert np.allclose(out["output"], ref_pred["output"])
        scrape = _get(port, "/metrics")[1].decode()
        burn = _metric(scrape, "fleet_slo_burn_rate", model="d",
                       slo_class="gold", window="1m")
        assert burn < 1.0, f"gold burn did not recover: {burn}"
        # the alert resolves because the CONDITION cleared (post-heal gold
        # traffic refreshed the gauge below threshold) — not because the
        # sustain window slid past the spike
        scraper.scrape_once()
        assert "gold_burn_high" not in engine.active(), engine.active()
        alerts_view = json.loads(_get(port, "/v1/alerts")[1])
        assert alerts_view["rules"]["gold_burn_high"]["state"] == "ok", \
            alerts_view["rules"]["gold_burn_high"]
        fired = [f for f in alerts_view["firings"]
                 if f["rule"] == "gold_burn_high"]
        assert fired and fired[-1]["resolved_at_s"] is not None, fired
        # ...and the resolution notice went out exactly once, closing the
        # dedup key the firing opened
        gb_res = [n for n in notifications if n["rule"] == "gold_burn_high"
                  and n["state"] == "resolved"]
        assert len(gb_res) == 1, gb_res
        assert gb_res[0]["dedup_key"] == gb_fired[0]["dedup_key"], \
            (gb_fired, gb_res)

        # ---- E: the router's tenant bucket is global, typed, and bounded
        print("=== phase E: global tenant quota ===", flush=True)
        for _ in range(2):
            _post(port, "/v1/models/d/predict", {"ndarray": X},
                  tenant="capped")
        code, cause, hdrs = _typed_error(
            port, "/v1/models/d/predict", {"ndarray": X}, tenant="capped")
        assert code == 429 and cause == "quota", (code, cause)
        assert hdrs.get("Retry-After") is not None

        # ---- final: counters moved, expositions valid, artifacts written
        scrape = _get(port, "/metrics")[1].decode()
        with open(os.path.join(artifacts, "smoke_cluster_metrics.prom"),
                  "w") as f:
            f.write(scrape)
        assert _metric(scrape, "cluster_replica_transitions_total",
                       to="dead") >= 1
        # exactly two deliveries for the burn page: the firing notice and
        # its resolution — the extra evaluate() while firing was deduped
        assert _metric(scrape, "alert_notifications_total",
                       rule="gold_burn_high", channel="capture",
                       outcome="sent") == 2.0
        assert _metric(scrape, "alert_notifications_total",
                       rule="gold_burn_high", channel="capture",
                       outcome="dedup") >= 1.0
        assert _metric(scrape, "cluster_heartbeats_total",
                       outcome="miss") >= 1
        assert _metric(scrape, "cluster_failover_total") >= 1
        assert _metric(scrape, "cluster_hedges_total",
                       outcome="launched") >= 1
        assert _metric(scrape, "cluster_hedges_total", outcome="won") >= 1
        assert _metric(scrape, "cluster_placement_rebuilds_total") >= 2
        assert _metric(scrape, "cluster_retry_budget_spend_total",
                       outcome="granted") >= 2
        assert _metric(scrape, "cluster_requests_total", outcome="ok") >= 10
        assert _metric(scrape, "serve_shed_total", cause="quota") >= 1
        # per-replica burn is exported alongside the per-model burn
        _metric(scrape, "fleet_slo_burn_rate", replica=survivor,
                slo_class="gold", window="1m")
        # telemetry-plane self-metrics rode along in the same exposition:
        # promcheck gates the tsdb_*/alert_* families with everything else
        assert _metric(scrape, "tsdb_scrapes_total", outcome="ok") >= 4
        assert _metric(scrape, "tsdb_points_total", source="router") >= 1
        assert _metric(scrape, "tsdb_series") >= 1
        assert _metric(scrape, "alert_transitions_total",
                       rule="gold_burn_high", to="firing") == 1
        assert _metric(scrape, "alert_transitions_total",
                       rule="gold_burn_high", to="resolved") == 1
        assert _metric(scrape, "alert_state", rule="gold_burn_high") == 0
        with open(os.path.join(artifacts, "smoke_cluster_tsdb.json"),
                  "w") as f:
            json.dump(tsdb_view, f, indent=1, sort_keys=True)
        errors = check_text(scrape, openmetrics=False)
        assert not errors, f"invalid /metrics exposition: {errors[:5]}"
        om = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text"}),
            timeout=30).read().decode()
        with open(os.path.join(artifacts,
                               "smoke_cluster_metrics_om.prom"), "w") as f:
            f.write(om)
        errors = check_text(om)
        assert not errors, f"invalid OpenMetrics exposition: {errors[:5]}"

        tracer.export(os.path.join(artifacts, "smoke_cluster_trace.json"))
        dump_path = recorder.dump("cluster_drill")
        assert dump_path is not None, "flight recorder refused to dump"
        with open(dump_path) as f:
            dumped = json.load(f)
        assert any(r["trace_id"] == hedge_trace
                   for r in dumped["requests"]), \
            "hedged request's record missing from the flight dump"
        # the alert lifecycle left its transitions in the same black box
        alert_evs = [(e.get("name"), e.get("detail"))
                     for e in dumped.get("events", [])
                     if e.get("kind") == "alert"]
        assert ("gold_burn_high", "firing") in alert_evs, alert_evs
        assert ("gold_burn_high", "resolved") in alert_evs, alert_evs
    finally:
        uninstall()
        router.stop()
        for h in replicas.values():
            if h.alive():
                h.stop()
        reqtrace_mod.uninstall()
        flight_mod.uninstall()

    # nothing left running: router, heartbeat, replicas, batchers all down
    import threading
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        hung = [t for t in threading.enumerate()
                if t.name.startswith(("serve-", "fleet-", "cluster-"))
                and t.is_alive()]
        if not hung:
            break
        time.sleep(0.1)
    assert not hung, f"threads left hanging: {[t.name for t in hung]}"
    print("smoke cluster OK: replica death survived, placement healed, "
          "hedge stitched, burn recovered, alert fired and resolved, "
          "no hung threads")


if __name__ == "__main__":
    main()
