#!/usr/bin/env python
"""CI smoke elastic: one seeded drill through the elastic trainer — the
ISSUE-19 acceptance surface, asserted end to end on forced-CPU devices.

The drill (deterministic, fixed seed, logical-clock supervision — nothing
waits on wall time):

- **A. uninterrupted prefix** — an ``ElasticTrainer`` at dp=4 warms every
  ladder width's ZeRO-1 pstep through the AOT store and trains 3 steps.
- **B. chaos kill -> reap -> reshard** — an ``elastic.step`` fault kills
  worker ``w1`` mid-epoch; its lease ages out on the logical clock, the
  membership sweep reaps it, and the mesh reshards dp=4 -> 3: atomic
  checkpoint at the old layout, planner-bounded redistribution (moved
  bytes strictly under the naive full re-gather), checkpoint at the new
  layout. The run then finishes at dp=3 with ZERO additional pstep
  traces — the resize resolved its executable from the warm store.
- **C. bit-identical comparator** — a second trainer resumes from the
  published post-resize checkpoint at dp=3 in the same process (its
  psteps deserialize from the store: zero live traces at boot) and
  trains to the same step count. Final loss AND every param /
  optimizer-state leaf must match run B **bit-for-bit**.
- **D. mid-resize death** — a second workdir arms ``elastic.resize``:
  the coordinator dies between the pre-resize checkpoint and the
  layout change. The failure is TYPED (chaos RuntimeError), the
  pointer still names the pre-resize dp=4 triple, and a resume at dp=3
  redistributes the dp=4 checkpoint onto the new layout and finishes.

Artifacts: $CI_ARTIFACTS_DIR/smoke_elastic_metrics.prom (validated by
obs.promcheck) and smoke_elastic_report.json (resize records + the
bit-identity verdict).
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np

STEPS_PREFIX = 3
STEPS_KILL = 8
STEPS_FINAL = 12


def _net():
    from deeplearning4j_tpu.nn import NetConfig, SequentialBuilder
    from deeplearning4j_tpu.nn import layers as L

    # hidden 24 / output 12 divide by every ladder width 2..4, so the
    # optimizer state genuinely shards (and genuinely moves) at each rung
    return (SequentialBuilder(NetConfig(seed=0, updater={"type": "adam",
                                                         "learning_rate": 1e-2}))
            .input_shape(8)
            .layer(L.Dense(n_out=24, activation="relu"))
            .layer(L.Output(n_out=12, activation="softmax", loss="mcxent"))
            .build())


def _batch(step):
    # a pure function of the step index: the killed run and the resumed
    # comparator replay the exact same byte stream
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(12, 8).astype(np.float32)
    y = np.eye(12, dtype=np.float32)[rng.randint(0, 12, 12)]
    return x, y


def _metric(scrape: str, name: str, **labels) -> float:
    total = 0.0
    found = False
    for line in scrape.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in "{ ":
            continue  # a longer metric name sharing this prefix
        if not all(f'{k}="{v}"' in rest for k, v in labels.items()):
            continue
        total += float(line.rsplit(" ", 1)[1])
        found = True
    assert found, f"metric {name}{labels or ''} missing from scrape"
    return total


def _assert_bit_identical(a, b, what):
    import jax

    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb), (what, len(la), len(lb))
    for (pa, va), (pb, vb) in zip(la, lb):
        assert pa == pb, (what, pa, pb)
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                      err_msg=f"{what} leaf {pa} diverged")


def main():
    artifacts = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(artifacts, exist_ok=True)

    from deeplearning4j_tpu.chaos import FaultPlane, install, uninstall
    from deeplearning4j_tpu.elastic import ElasticTrainer, latest
    from deeplearning4j_tpu.obs.metrics import MetricsRegistry
    from deeplearning4j_tpu.obs.promcheck import check_text

    reg = MetricsRegistry()
    wd = tempfile.mkdtemp(prefix="smoke_elastic_")
    wd2 = tempfile.mkdtemp(prefix="smoke_elastic_midresize_")
    report = {"schema": "smoke_elastic/1"}
    try:
        # ---- A: uninterrupted prefix at dp=4, store-warmed ladder
        print("=== phase A: dp=4 prefix, all ladder psteps AOT-warmed ===",
              flush=True)
        t = ElasticTrainer(_net(), workdir=wd, dp=4, dp_min=2, seed=0,
                           metrics=reg)
        t.fit(_batch, STEPS_PREFIX)
        boot_traces = t.trace_count()
        assert t.dp == 4 and not t.resizes

        # ---- B: chaos-kill w1 -> lease ages out -> reap -> dp=4 -> 3
        print("=== phase B: kill w1 -> reap -> reshard 4->3 ===", flush=True)
        fp = FaultPlane(seed=0, metrics=reg).inject_spec(
            "elastic.step:error:scope=w1,times=1")
        install(fp)
        try:
            t.fit(_batch, STEPS_KILL)
        finally:
            uninstall()
        assert fp.injected().get(("elastic.step", "error")) == 1
        assert t.dp == 3, f"mesh did not reshard: dp={t.dp}"
        assert [r["cause"] for r in t.resizes] == ["worker_death"]
        rec = t.resizes[0]
        assert (rec["from"], rec["to"]) == (4, 3)
        assert 0 < rec["bytes_moved"] < rec["bytes_naive"], rec
        info = latest(wd)
        assert info is not None and info.dp == 3
        assert info.cause.startswith("post_resize"), info
        assert info.mesh_shape == (("data", 3),)

        t.fit(_batch, STEPS_FINAL)
        final_a = t.final_loss()
        assert t.trace_count() == boot_traces, \
            (f"post-resize compile miss: {t.trace_count() - boot_traces} "
             f"live trace(s) after warm()")
        report["resizes"] = t.resizes
        report["boot_traces"] = boot_traces
        report["final_loss"] = final_a

        # ---- C: resume the published checkpoint at dp=3, bit-identity
        print("=== phase C: resumed comparator, bit-identity ===", flush=True)
        t2 = ElasticTrainer.resume(wd, dp=3, seed=0, metrics=reg)
        assert t2.iteration == info.step and t2.dp == 3
        t2.fit(_batch, STEPS_FINAL)
        assert t2.trace_count() == 0, \
            "comparator cold-traced despite the warm AOT store"
        final_b = t2.final_loss()
        assert final_b == final_a, (final_a, final_b)
        _assert_bit_identical(t.params, t2.params, "params")
        _assert_bit_identical(t.opt_state, t2.opt_state, "opt_state")
        report["comparator_loss"] = final_b
        report["bit_identical"] = True

        # ---- D: death mid-resize -> typed error -> pre-resize resume
        print("=== phase D: mid-resize death, pre-resize resume ===",
              flush=True)
        # a fresh workdir means a fresh (cold) store: phase D's boot
        # traces go to their own registry so the phase-B/C trace ledger
        # on ``reg`` stays exact
        reg2 = MetricsRegistry()
        t3 = ElasticTrainer(_net(), workdir=wd2, dp=4, dp_min=2, seed=0,
                            metrics=reg2)
        t3.fit(_batch, STEPS_PREFIX)
        fp = (FaultPlane(seed=0, metrics=reg)
              .inject_spec("elastic.step:error:scope=w2,times=1")
              .inject_spec("elastic.resize:error:times=1"))
        install(fp)
        try:
            t3.fit(_batch, STEPS_KILL)
            raise AssertionError("mid-resize chaos error did not surface")
        except RuntimeError as e:
            assert "elastic.resize" in str(e), f"untyped failure: {e!r}"
        finally:
            uninstall()
        info3 = latest(wd2)
        assert info3 is not None and info3.dp == 4
        assert info3.cause.startswith("pre_resize"), info3
        t4 = ElasticTrainer.resume(wd2, dp=3, seed=0, metrics=reg2)
        assert t4.dp == 3 and t4.iteration == info3.step
        assert t4.resizes and t4.resizes[-1]["cause"] == "resume"
        t4.fit(_batch, STEPS_KILL)
        assert t4.iteration == STEPS_KILL
        report["mid_resize"] = {"pointer_cause": info3.cause,
                                "resumed_dp": t4.dp,
                                "resumed_step": int(info3.step)}

        # ---- final: every elastic metric family moved, exposition valid
        scrape = reg.to_prometheus()
        with open(os.path.join(artifacts, "smoke_elastic_metrics.prom"),
                  "w") as f:
            f.write(scrape)
        assert _metric(scrape, "elastic_resizes_total",
                       cause="worker_death") == 1.0
        assert _metric(scrape, "elastic_reshard_bytes_total") > 0
        assert _metric(scrape, "elastic_step_seconds_count") >= STEPS_FINAL
        assert _metric(scrape, "elastic_checkpoint_seconds_count") >= 2
        assert _metric(scrape, "elastic_resize_seconds_count") == 1.0
        assert _metric(scrape, "elastic_dp") == 3.0
        assert _metric(scrape, "elastic_pstep_traces_total") == boot_traces
        assert _metric(scrape, "chaos_faults_injected_total",
                       point="elastic.step", mode="error") == 2.0
        assert _metric(scrape, "chaos_faults_injected_total",
                       point="elastic.resize", mode="error") == 1.0
        errs = check_text(scrape, openmetrics=False)
        assert not errs, f"invalid exposition: {errs[:5]}"

        with open(os.path.join(artifacts, "smoke_elastic_report.json"),
                  "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
    finally:
        shutil.rmtree(wd, ignore_errors=True)
        shutil.rmtree(wd2, ignore_errors=True)

    # nothing left running: the trainer is loop-in-process by design
    hung = [th for th in threading.enumerate()
            if th.name.startswith(("serve-", "fleet-", "cluster-",
                                   "autoscale-", "elastic-"))
            and th.is_alive()]
    assert not hung, f"threads left hanging: {[th.name for th in hung]}"

    print("smoke elastic OK: worker reaped, mesh resharded 4->3 with "
          f"{report['resizes'][0]['bytes_moved']} B moved "
          f"(naive {report['resizes'][0]['bytes_naive']} B), zero "
          "post-resize traces, resumed comparator bit-identical "
          f"(loss {report['final_loss']:.6f}), mid-resize death resumed "
          "from the pre-resize triple")


if __name__ == "__main__":
    main()
