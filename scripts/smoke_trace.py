#!/usr/bin/env python
"""CI smoke train: 5 telemetry-instrumented steps on CPU, exporting the
Chrome-trace JSON and Prometheus scrape as build artifacts.

Asserts the ISSUE-2 acceptance surface — the scrape must contain the
``train_step_seconds`` histogram, ``compile_cache_misses_total`` counter,
and ``device_memory_bytes`` gauge, and the trace must be Perfetto-loadable
(valid JSON, ``traceEvents`` with complete events) — so a regression in the
telemetry path fails CI before it reaches a real TPU run.

Artifacts land in $CI_ARTIFACTS_DIR (default: ./ci-artifacts/):
smoke_trace.json (open at https://ui.perfetto.dev) and smoke_metrics.prom.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from deeplearning4j_tpu.data import ArrayIterator
from deeplearning4j_tpu.nn.layers import Dense, Output
from deeplearning4j_tpu.nn.model import NetConfig, Sequential
from deeplearning4j_tpu.obs import StepTelemetry
from deeplearning4j_tpu.train import Trainer

STEPS = 5
BATCH = 16


def main() -> int:
    out_dir = os.environ.get("CI_ARTIFACTS_DIR", "ci-artifacts")
    os.makedirs(out_dir, exist_ok=True)

    rng = np.random.RandomState(0)
    x = rng.rand(STEPS * BATCH, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, STEPS * BATCH)]
    model = Sequential(
        NetConfig(updater={"type": "sgd", "learning_rate": 0.1}),
        [Dense(n_out=8, activation="relu"),
         Output(n_out=3, loss="mcxent", activation="softmax")], (5,))
    tel = StepTelemetry()
    Trainer(model).fit(ArrayIterator(x, y, batch_size=BATCH), epochs=1,
                       telemetry=tel)

    trace_path = os.path.join(out_dir, "smoke_trace.json")
    prom_path = os.path.join(out_dir, "smoke_metrics.prom")
    tel.export_trace(trace_path)
    prom = tel.to_prometheus()
    with open(prom_path, "w") as f:
        f.write(prom)

    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert any(e.get("ph") == "X" and e.get("name") == "train_step"
               for e in events), "no train_step span in trace"
    assert all({"name", "ph", "pid", "tid"} <= set(e) for e in events), \
        "malformed trace event"
    for needle in ("train_step_seconds_bucket", "compile_cache_misses_total",
                   "device_memory_bytes"):
        assert needle in prom, f"missing {needle} in Prometheus scrape"
    snap = tel.snapshot()
    assert snap["steps"] == STEPS, f"expected {STEPS} steps, got {snap['steps']}"

    print(f"smoke_trace: {snap['steps']} steps, "
          f"{snap['steps_per_sec']:.1f} steps/sec, "
          f"{snap['compile_cache_misses']} compile(s), "
          f"{len(events)} trace events -> {trace_path}, {prom_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
