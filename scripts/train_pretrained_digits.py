#!/usr/bin/env python
"""Train the bundled REAL pretrained checkpoint (r4 VERDICT #5).

The reference's ZooModel.initPretrained() serves genuinely trained weights
through a checksum-verified download cache (ZooModel.java:40-81). This
environment has no egress, so the real weight set is produced here: LeNet
trained to convergence on scikit-learn's bundled handwritten-digits set —
REAL images (1,797 8x8 grayscale scans of human-written digits, the UCI
optdigits test partition sklearn vendors inside the wheel), not synthetic.

Images are nearest-neighbor upscaled 8x8 -> 28x28 so the zoo LeNet's
standard MNIST-shaped architecture is exercised unchanged. A held-out
test split gates publication (>= 0.95 accuracy required); the checkpoint
+ sha256 sidecar land in tests/data/pretrained/, which
tests/test_pretrained.py serves through the production cache (CACHE_DIR
override) and asserts real predictions against real images.

Runs on CPU in ~1 minute. Deterministic (fixed seeds, fixed split).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

OUT_DIR = os.path.join(REPO, "tests", "data", "pretrained")


def load_real_digits():
    """Real handwritten digits from sklearn, upscaled to LeNet's 28x28,
    deterministic 80/20 split."""
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = d.images.astype(np.float32) / 16.0  # (N, 8, 8) in [0, 1]
    x = imgs.repeat(4, axis=1).repeat(4, axis=2)[..., None]  # 32x32
    x = x[:, 2:-2, 2:-2, :]  # center-crop to 28x28
    y = np.eye(10, dtype=np.float32)[d.target]
    rng = np.random.RandomState(0)
    idx = rng.permutation(len(x))
    n_tr = int(0.8 * len(x))
    tr, te = idx[:n_tr], idx[n_tr:]
    return (x[tr], y[tr]), (x[te], y[te]), d.target


def main():
    from deeplearning4j_tpu.data.iterators import ArrayIterator
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.train import Trainer

    (xtr, ytr), (xte, yte), _ = load_real_digits()
    print(f"real digits: train {xtr.shape[0]}, test {xte.shape[0]}")

    zm = LeNet(num_classes=10, seed=0)
    model = zm.init()
    tr = Trainer(model)
    acc = 0.0
    for stage in range(4):  # 3 epochs per stage, report between stages
        tr.fit(ArrayIterator(xtr, ytr, 64, shuffle=True), epochs=3)
        pred = np.argmax(np.asarray(model.output(xte)), axis=1)
        acc = float((pred == np.argmax(yte, axis=1)).mean())
        print(f"after {(stage + 1) * 3} epochs: test acc {acc:.4f}")
        if acc >= 0.97:
            break
    assert acc >= 0.95, f"did not converge: {acc}"

    # publish into tests/data/pretrained (pretrained_path resolves
    # zoo.CACHE_DIR at call time, so patching the module global is enough)
    from pathlib import Path

    from deeplearning4j_tpu.models import zoo as zoo_mod

    zoo_mod.CACHE_DIR = Path(OUT_DIR)
    path = LeNet(num_classes=10, seed=0).save_pretrained(model, "digits")
    print(f"published: {path} (+ .sha256), test acc {acc:.4f}")


if __name__ == "__main__":
    main()
