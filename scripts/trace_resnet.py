#!/usr/bin/env python
"""Capture a device trace of the ResNet-50 train step and print the top ops.

Uses jax.profiler to write an xplane proto, then parses it with the
tensorboard profile plugin's raw-to-tool converter to get per-op self time.
"""

import glob
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

BATCH = int(os.environ.get("PROF_BATCH", 128))
IMG = 224


def main():
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.train import Trainer

    zm = ResNet50(num_classes=1000, seed=0, input_shape=(IMG, IMG, 3))
    model = zm.build()
    model.config.compute_dtype = "bfloat16"
    model.init()
    tr = Trainer(model)
    step = tr._make_step()

    x = jax.device_put(np.random.RandomState(0).rand(BATCH, IMG, IMG, 3).astype(np.float32))
    y = jax.device_put(np.eye(1000, dtype=np.float32)[
        np.random.RandomState(1).randint(0, 1000, BATCH)])
    rng = jax.random.PRNGKey(0)

    p, o, s = tr.params, tr.opt_state, tr.state
    for _ in range(3):  # compile + warm
        p, o, s, loss = step(p, o, s, x, y, rng)
    float(loss)

    logdir = tempfile.mkdtemp(prefix="rn50trace")
    with jax.profiler.trace(logdir):
        for _ in range(8):
            p, o, s, loss = step(p, o, s, x, y, rng)
        float(loss)

    xplanes = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    print("xplane files:", xplanes, file=sys.stderr)
    if not xplanes:
        sys.exit("no xplane captured")

    from tensorboard_plugin_profile.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data(xplanes, "framework_op_stats", {})
    import gzip
    import json

    if isinstance(data, bytes):
        try:
            data = gzip.decompress(data)
        except OSError:
            pass
        data = data.decode()
    parsed = json.loads(data)
    # framework_op_stats: list-of-tables; find the op table rows
    print(json.dumps(parsed, indent=1)[:4000])


if __name__ == "__main__":
    main()
