#!/usr/bin/env python
"""Fast wedge-safe chip probe: daemon-thread jax.devices() + a tiny matmul
with scalar readback, joined with a timeout. Exits 0/OK only if the chip
actually computed something. Never wrap chip work in `timeout` — a SIGTERM
mid-flight re-wedges the tunnel; this probe's main thread just exits and
leaves the daemon thread behind instead."""
import os
import sys
import threading

out = {}


def probe():
    import jax
    import jax.numpy as jnp
    out["d"] = jax.devices()
    x = jnp.ones((256, 256))
    out["v"] = float((x @ x).sum())  # D2H readback = real execution proof


t = threading.Thread(target=probe, daemon=True)
t.start()
t.join(75)
if "v" in out:
    print(f"OK {out['d']} sum={out['v']}")
    sys.exit(0)
print("WEDGED" + (" (devices visible, exec hung)" if "d" in out else ""))
os._exit(3)  # plain sys.exit can hang joining PJRT threads
